"""The replica-fleet front-end (ISSUE 13): router, failover, restarts.

Four layers, cheapest first:

* jax-free units: the HEALTHY → EJECTED → PROBATION → HEALTHY state
  machine, the smooth-WRR picker, the `fleet` fault site (incl. the new
  ``kinds`` budget-isolation filter);
* router drills against FAKE stdlib replicas (no jax): routing spread,
  payload truth fields, transport-death failover, 503 backpressure
  rerouting + Retry-After propagation, application-verdict passthrough,
  health-poll ejection + probation reinstatement, deterministic
  fault-site drills;
* `nm03-loadgen --targets` multi-target mode + the check_telemetry
  fleet-gate red/green battery (labeled selectors whose `replica` values
  carry `:` — the host:port form the drills assert on);
* rolling-restart orchestration against dummy restartable subprocess
  replicas, and the two subprocess acceptance drills on REAL
  ``nm03-serve`` replicas: SIGKILL-a-replica mid-loadgen (zero failed
  requests, failovers observed, the ⅔ plateau live, probation heal) and
  ``nm03-fleet restart`` with a shared compile cache under concurrent
  load (capacity never below ⅔, ``builds == 0`` warm restarts, zero
  loadgen errors).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from nm03_capstone_project_tpu.fleet.replicas import (
    EJECTED,
    HEALTHY,
    PROBATION,
    ReplicaStates,
    normalize_target,
    target_label,
)
from nm03_capstone_project_tpu.fleet.router import FleetApp, serve_in_thread
from nm03_capstone_project_tpu.resilience import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


class _Events:
    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, event, level="INFO", **fields):
        with self._lock:
            self.records.append({"event": event, "level": level, **fields})

    def of(self, event):
        with self._lock:
            return [r for r in self.records if r["event"] == event]


class _Obs:
    """Minimal RunContext stand-in: real registry, recorded events."""

    def __init__(self):
        from nm03_capstone_project_tpu.obs.metrics import MetricsRegistry

        self.registry = MetricsRegistry()
        self.events = _Events()
        self.faults = []

    def fault_injected(self, **kw):
        self.faults.append(kw)
        self.registry.counter(
            "resilience_faults_injected_total",
            site=kw.get("site", ""), kind=kw.get("kind", ""),
        ).inc()

    def metrics_snapshot(self):
        return self.registry.snapshot(run_id="t", git_sha="t")

    def write_metrics(self, path=None):
        pass

    def close(self, status="ok", **kw):
        pass


# -- the state machine -------------------------------------------------------


class TestReplicaStates:
    def _mk(self, n=3, obs=None):
        return ReplicaStates(
            [f"127.0.0.1:{9000 + i}" for i in range(n)], obs=obs
        )

    def test_initial_all_healthy_with_gauges(self):
        obs = _Obs()
        rs = self._mk(3, obs)
        assert rs.healthy_count() == 3 and rs.ejected_count() == 0
        for i in range(3):
            g = obs.registry.get(
                "fleet_replica_state", replica=f"127.0.0.1:{9000 + i}"
            )
            assert g is not None and g.value == 0

    def test_normalization_and_labels(self):
        assert normalize_target("h:1/") == "http://h:1"
        assert normalize_target("https://h:1") == "https://h:1"
        assert target_label("http://127.0.0.1:8123") == "127.0.0.1:8123"
        with pytest.raises(ValueError):
            ReplicaStates([])
        with pytest.raises(ValueError):
            ReplicaStates(["h:1", "http://h:1"])  # duplicates post-normalize

    def test_eject_transition_and_telemetry(self):
        obs = _Obs()
        rs = self._mk(3, obs)
        t = rs.targets[1]
        changed, left = rs.eject(t, "refused")
        assert changed and left == 2
        assert rs.state(t) == EJECTED and rs.cause(t) == "refused"
        assert rs.healthy_targets() == [rs.targets[0], rs.targets[2]]
        assert obs.registry.get(
            "fleet_replica_state", replica=target_label(t)
        ).value == 2
        assert obs.registry.get(
            "fleet_replica_ejections_total",
            replica=target_label(t), cause="refused",
        ).value == 1
        ev = obs.events.of("replica_ejected")
        assert len(ev) == 1 and ev[0]["level"] == "WARNING"
        assert ev[0]["healthy_remaining"] == 2

    def test_eject_idempotent_for_non_healthy(self):
        obs = _Obs()
        rs = self._mk(2, obs)
        t = rs.targets[0]
        assert rs.eject(t, "timeout") == (True, 1)
        # a proxy failure on an already-ejected replica: same incident
        assert rs.eject(t, "proxy_error") == (False, 1)
        assert rs.cause(t) == "timeout"  # the first verdict stands
        rs.begin_probation(t)
        # a stale failure cannot steal the canary claim either
        assert rs.eject(t, "proxy_error") == (False, 1)
        assert rs.state(t) == PROBATION
        assert obs.registry.get(
            "fleet_replica_ejections_total",
            replica=target_label(t), cause="timeout",
        ).value == 1

    def test_probation_claim_exclusive_and_reinstate(self):
        obs = _Obs()
        rs = self._mk(2, obs)
        t = rs.targets[0]
        assert not rs.begin_probation(t)  # healthy: nothing to probe
        rs.eject(t, "refused")
        assert rs.begin_probation(t)
        assert not rs.begin_probation(t)  # second prober bounced
        assert not rs.reinstate(rs.targets[1])  # healthy: no-op
        assert rs.reinstate(t)
        assert rs.state(t) == HEALTHY and rs.cause(t) is None
        assert rs.healthy_count() == 2
        assert obs.registry.get(
            "fleet_replica_reinstated_total", replica=target_label(t)
        ).value == 1

    def test_fail_probation_recounts_as_fresh_ejection(self):
        obs = _Obs()
        rs = self._mk(2, obs)
        t = rs.targets[1]
        rs.eject(t, "http_503")
        rs.begin_probation(t)
        assert rs.fail_probation(t)
        assert rs.state(t) == EJECTED and rs.cause(t) == "probe_failed"
        assert obs.registry.get(
            "fleet_replica_ejections_total",
            replica=target_label(t), cause="probe_failed",
        ).value == 1

    def test_signals_feed_weight_and_capacity(self):
        rs = self._mk(3)
        a, b, c = rs.targets
        rs.update_signals(a, capacity=1.0, queue_depth=0, queue_capacity=64)
        rs.update_signals(b, capacity=0.5, queue_depth=32, queue_capacity=64)
        rs.update_signals(c, capacity=0.75)
        assert rs.weight(a) == 1.0
        assert rs.weight(b) == pytest.approx(0.25)  # 0.5 cap x 0.5 headroom
        assert rs.weight(c) == 0.75  # no queue signals -> full headroom
        assert rs.capacity_fraction() == pytest.approx((1.0 + 0.5 + 0.75) / 3)
        rs.eject(b, "refused")
        assert rs.capacity_fraction() == pytest.approx((1.0 + 0.75) / 3)

    def test_snapshot_carries_the_router_table(self):
        rs = self._mk(2)
        rs.update_signals(
            rs.targets[0], capacity=1.0, identity={"id": "abc", "pid": 7}
        )
        rs.eject(rs.targets[1], "timeout")
        snap = rs.snapshot()
        assert [r["state"] for r in snap] == [HEALTHY, EJECTED]
        assert snap[0]["identity"] == {"id": "abc", "pid": 7}
        assert snap[1]["cause"] == "timeout" and snap[1]["ejections"] == 1

    def test_obs_none_is_fine(self):
        rs = self._mk(2, obs=None)
        rs.eject(rs.targets[0], "refused")
        rs.begin_probation(rs.targets[0])
        rs.reinstate(rs.targets[0])
        assert rs.healthy_count() == 2


# -- the picker --------------------------------------------------------------


class TestWeightedPick:
    def _app(self, n=3, obs=None):
        app = FleetApp(
            [f"127.0.0.1:{9100 + i}" for i in range(n)],
            obs=obs or _Obs(), health_interval_s=3600,
        )
        return app

    def test_spread_is_proportional_to_weights(self):
        app = self._app(3)
        a, b, c = app.replicas.targets
        app.replicas.update_signals(a, capacity=1.0)
        app.replicas.update_signals(b, capacity=1.0)
        app.replicas.update_signals(c, capacity=0.5)
        picks = [app.pick() for _ in range(100)]
        counts = {t: picks.count(t) for t in (a, b, c)}
        assert counts[a] == pytest.approx(40, abs=3)
        assert counts[b] == pytest.approx(40, abs=3)
        assert counts[c] == pytest.approx(20, abs=3)

    def test_excludes_ejected_and_tried(self):
        app = self._app(3)
        a, b, c = app.replicas.targets
        app.replicas.eject(b, "refused")
        picks = {app.pick() for _ in range(10)}
        assert b not in picks and picks == {a, c}
        assert app.pick(exclude=frozenset({a, c})) is None

    def test_zero_weight_healthy_replica_still_pickable(self):
        app = self._app(1)
        (a,) = app.replicas.targets
        app.replicas.update_signals(
            a, capacity=1.0, queue_depth=64, queue_capacity=64
        )
        assert app.pick() == a  # the floor: full queue != unroutable


# -- fake replicas for router drills ----------------------------------------


class FakeReplica:
    """A stdlib stand-in for nm03-serve: /readyz + /v1/segment, mutable
    behavior (capacity, shed, drop-connection) and a request log."""

    def __init__(self, name, capacity=1.0):
        self.name = name
        self.capacity = capacity
        self.shed = False
        self.drop = False  # abort POST connections without a response
        self.canvas = None  # published request-size guards (None = omit)
        self.min_dim = None
        self.volumes = None  # the ISSUE 15 volumes block (None = omit)
        self.requests = []
        self._lock = threading.Lock()
        fake = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _j(self, status, body, headers=()):
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/readyz":
                    self._j(200, {
                        "ready": True, "capacity": fake.capacity,
                        "queue_depth": 0, "queue_capacity": 64,
                        "canvas": fake.canvas, "min_dim": fake.min_dim,
                        "volumes": fake.volumes,
                        "replica": {"id": fake.name, "pid": os.getpid()},
                        # the ISSUE 14 clock handshake: a fixed fake pair
                        # whose implied offset the router must record
                        "clock": {"mono_s": 5.0, "ts_unix": 1005.0},
                    })
                else:
                    self._j(200, {"status": "alive"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                with fake._lock:
                    fake.requests.append({
                        "path": self.path, "bytes": len(body),
                        "id": self.headers.get("X-Nm03-Request-Id"),
                        "probe": self.headers.get("X-Nm03-Probe"),
                    })
                if fake.drop:
                    # die mid-response: the transport failure the
                    # failover ladder exists for
                    self.wfile.flush()
                    self.connection.close()
                    return
                if fake.shed:
                    self._j(503, {"error": "queue full"},
                            [("Retry-After", "7")])
                    return
                if body and body[:1] == b"\xff":
                    self._j(400, {"error": "bad body"})
                    return
                self._j(200, {
                    "mask_pixels": 5, "lane": 0, "batch_size": 1,
                    "trace_id": self.headers.get("X-Nm03-Request-Id", "t"),
                    "queue_wait_s": 0.001,
                }, [("X-Nm03-Batch-Size", "1"), ("X-Nm03-Lane", "0"),
                    ("X-Nm03-Request-Id",
                     self.headers.get("X-Nm03-Request-Id", "t")),
                    ("X-Nm03-Queue-Wait-Ms", "1.0")])

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def label(self):
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _wait_state(app, target, state, timeout_s=15.0):
    """Wait for the (async, thread-spawned) probation canary's verdict."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if app.replicas.state(target) == state:
            return True
        time.sleep(0.02)
    return False


def _segment_body(hw=16):
    return bytes(hw * hw * 4), {
        "Content-Type": "application/octet-stream",
        "X-Nm03-Height": str(hw), "X-Nm03-Width": str(hw),
    }


@pytest.fixture
def two_fakes():
    a, b = FakeReplica("ra"), FakeReplica("rb")
    yield a, b
    a.stop()
    b.stop()


class TestRouterProxy:
    def _app(self, fakes, obs=None, **kw):
        kw.setdefault("health_interval_s", 3600)  # drills sweep by hand
        app = FleetApp([f.url for f in fakes], obs=obs or _Obs(), **kw)
        app._sweep()  # one informed pass, no background thread
        return app

    def test_routes_and_tells_the_truth(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        body, hdrs = _segment_body()
        seen = set()
        for _ in range(4):
            status, data, headers = app.proxy_segment(body, hdrs)
            assert status == 200
            p = json.loads(data)
            assert p["replica_hops"] == 0
            assert p["replica"] in (a.label, b.label)
            assert p["replica_id"] in ("ra", "rb")
            seen.add(p["replica"])
            hmap = dict(headers)
            assert hmap["X-Nm03-Replica"] == p["replica"]
            assert hmap["X-Nm03-Replica-Hops"] == "0"
            assert hmap["X-Nm03-Lane"] == "0"  # replica headers forwarded
        assert seen == {a.label, b.label}  # both replicas took traffic
        routed = [
            m for m in obs.registry.series()
            if m.name == "fleet_requests_routed_total"
        ]
        assert sum(m.value for m in routed) == 4 and len(routed) == 2

    def test_transport_death_fails_over_and_ejects(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        a.drop = True
        body, hdrs = _segment_body()
        status, data, headers = app.proxy_segment(body, hdrs)
        assert status == 200
        p = json.loads(data)
        assert p["replica"] == b.label and p["replica_hops"] == 1
        assert app.replicas.state(a.url) == EJECTED
        assert app.replicas.cause(a.url) == "proxy_error"
        assert obs.registry.get(
            "fleet_failovers_total", replica=a.label, cause="io_error"
        ).value == 1
        # the survivor keeps serving with no further hops
        status, data, _ = app.proxy_segment(body, hdrs)
        assert json.loads(data)["replica_hops"] == 0

    def test_shed_reroutes_while_alternative_exists(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        a.shed = True
        b.shed = False
        body, hdrs = _segment_body()
        for _ in range(3):
            status, data, _ = app.proxy_segment(body, hdrs)
            assert status == 200  # the healthy replica absorbs it
        # a shed is a reroute, not an ejection: backpressure != sickness
        assert app.replicas.state(a.url) == HEALTHY
        assert obs.registry.get("fleet_shed_total").value == 0

    def test_volume_request_weighs_its_depth_in_wrr(self, two_fakes):
        """ISSUE 15: a /v1/segment-volume proxy debits the picked replica
        its declared depth's worth of WRR rounds — the following slice
        picks all land on the OTHER replica until the debt amortizes."""
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        vol_body = bytes(4 * 16 * 16 * 4)
        vol_hdrs = {
            "Content-Type": "application/octet-stream",
            "X-Nm03-Depth": "4", "X-Nm03-Height": "16",
            "X-Nm03-Width": "16",
        }
        assert app.volume_request_cost(vol_hdrs) == 4.0
        status, data, _ = app.proxy_segment(
            vol_body, vol_hdrs, path="/v1/segment-volume", cost=4.0
        )
        assert status == 200
        volume_replica = json.loads(data)["replica"]
        fakes = {a.label: a, b.label: b}
        served_by = fakes[volume_replica]
        other = b if served_by is a else a
        # the volume reached the replica on the VOLUME endpoint
        assert any(
            r["path"].startswith("/v1/segment-volume")
            for r in served_by.requests
        )
        # cost 4: the next 3 slice picks amortize the debt elsewhere
        body, hdrs = _segment_body()
        for _ in range(3):
            _s, d2, _h = app.proxy_segment(body, hdrs)
            assert json.loads(d2)["replica"] == other.label
        # debt paid: traffic spreads again
        picked = {
            json.loads(app.proxy_segment(body, hdrs)[1])["replica"]
            for _ in range(4)
        }
        assert volume_replica in picked

    def test_unsized_volume_uses_published_cost(self, two_fakes):
        """No X-Nm03-Depth: the WRR weighs the request by the largest
        volume cost any replica published on /readyz (its smallest depth
        bucket), floor 1.0 when nobody serves volumes."""
        a, b = two_fakes
        app = self._app([a, b])
        assert app.volume_request_cost({}) == 1.0  # nobody publishes
        a.volumes = {"enabled": True, "default_cost": 16,
                     "depth_buckets": [16, 32]}
        app._sweep()
        assert app.volume_request_cost({}) == 16.0
        assert app.volume_request_cost({"X-Nm03-Depth": "nonsense"}) == 16.0

    def test_fleet_wide_shed_propagates_retry_after(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        a.shed = b.shed = True
        body, hdrs = _segment_body()
        status, data, headers = app.proxy_segment(body, hdrs)
        assert status == 503
        assert dict(headers)["Retry-After"] == "7"  # the replica's own
        assert obs.registry.get("fleet_shed_total").value == 1

    def test_application_verdicts_propagate_without_failover(self, two_fakes):
        a, b = two_fakes
        app = self._app([a, b])
        status, data, _ = app.proxy_segment(
            b"\xff" + bytes(15), _segment_body()[1]
        )
        assert status == 400
        assert json.loads(data)["error"] == "bad body"
        # a deterministic rejection must not burn the other replica
        assert len(a.requests) + len(b.requests) == 1
        assert app.replicas.healthy_count() == 2

    def test_no_healthy_replica_is_a_503_with_hint(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        a.drop = b.drop = True
        body, hdrs = _segment_body()
        status, data, headers = app.proxy_segment(body, hdrs)
        assert status == 503
        assert "no healthy replica" in json.loads(data)["error"]
        assert dict(headers)["Retry-After"] == "1"
        assert app.replicas.healthy_count() == 0
        assert obs.registry.get("fleet_shed_total").value == 1


class TestRouterHealthLoop:
    def test_dead_replica_ejected_and_probation_reinstates(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = FleetApp(
            [a.url, b.url], obs=obs,
            health_interval_s=3600, probe_interval_s=0.0, canary_timeout_s=5.0,
        )
        app._sweep()
        assert app.replicas.healthy_count() == 2
        # kill a: next sweep ejects (refused), readyz stays informative
        a.stop()
        app._sweep()
        assert app.replicas.state(a.url) == EJECTED
        st = app.status()
        assert st["ready"] is True and st["capacity"] == 0.5
        assert st["replicas"]["ready"] == 1 and st["replicas"]["ejected"] == 1
        # bring a back on the SAME port: poll ok -> canary -> reinstated
        b2 = _fresh_fake_on_port("ra2", a.port)
        try:
            app._sweep()
            assert _wait_state(app, a.url, HEALTHY)
            assert obs.registry.get(
                "fleet_probes_total", replica=a.label, outcome="passed"
            ).value == 1
            assert obs.registry.get(
                "fleet_replica_reinstated_total", replica=a.label
            ).value == 1
            assert app.status()["capacity"] == 1.0
        finally:
            b2.stop()

    def test_zero_capacity_and_503_eject(self, two_fakes):
        a, b = two_fakes
        app = FleetApp(
            [a.url, b.url], obs=_Obs(),
            health_interval_s=3600, probe_interval_s=3600,
        )
        a.capacity = 0.0
        app._sweep()
        assert app.replicas.state(a.url) == EJECTED
        assert app.replicas.cause(a.url) == "zero_capacity"

    def test_canary_sizes_itself_inside_the_replica_guards(self, two_fakes):
        """The live-drill regression: a replica publishing min_dim=100
        must get a >=100x100 canary, not the 32x32 default its guards
        would 400 — an ejection that can never heal."""
        a, b = two_fakes
        a.min_dim, a.canvas = 100, 128  # published on /readyz (below)
        obs = _Obs()
        app = FleetApp(
            [a.url, b.url], obs=obs,
            health_interval_s=3600, probe_interval_s=0.0, canary_timeout_s=5.0,
        )
        app._sweep()
        app.replicas.eject(a.url, "proxy_error")
        app._sweep()  # poll ok -> canary sized 100x100 -> reinstated
        assert _wait_state(app, a.url, HEALTHY)
        canaries = [r for r in a.requests if (r["id"] or "").startswith(
            "fleet-probe-")]
        assert canaries and canaries[-1]["bytes"] == 100 * 100 * 4

    def test_failed_canary_returns_to_ejected(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = FleetApp(
            [a.url, b.url], obs=obs,
            health_interval_s=3600, probe_interval_s=0.0, canary_timeout_s=5.0,
        )
        app._sweep()
        app.replicas.eject(a.url, "proxy_error")
        a.shed = True  # readyz fine, canary POST 503s -> probe fails
        app._sweep()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not (
            app.replicas.state(a.url) == EJECTED
            and app.replicas.cause(a.url) == "probe_failed"
        ):
            time.sleep(0.02)
        assert app.replicas.state(a.url) == EJECTED
        assert app.replicas.cause(a.url) == "probe_failed"
        assert obs.registry.get(
            "fleet_probes_total", replica=a.label, outcome="failed"
        ).value == 1


def _fresh_fake_on_port(name: str, port: int) -> FakeReplica:
    """A FakeReplica bound to a SPECIFIC port (a revived replica —
    retries through the closed listener's TIME_WAIT window)."""
    fake = object.__new__(FakeReplica)
    fake.name = name
    fake.capacity = 1.0
    fake.shed = False
    fake.drop = False
    fake.requests = []
    fake._lock = threading.Lock()

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _j(self, status, body, headers=()):
            data = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/readyz":
                self._j(200, {
                    "ready": True, "capacity": fake.capacity,
                    "queue_depth": 0, "queue_capacity": 64,
                    "replica": {"id": name, "pid": os.getpid()},
                })
            else:
                self._j(200, {"status": "alive"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            if fake.shed:
                self._j(503, {"error": "full"}, [("Retry-After", "7")])
            else:
                self._j(200, {"mask_pixels": 5, "lane": 0, "batch_size": 1,
                              "trace_id": "t", "queue_wait_s": 0.0})

    deadline = time.monotonic() + 10
    while True:
        try:
            fake.httpd = ThreadingHTTPServer(("127.0.0.1", port), H)
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    fake.httpd.daemon_threads = True
    fake.port = port
    fake.url = f"http://127.0.0.1:{port}"
    threading.Thread(target=fake.httpd.serve_forever, daemon=True).start()
    return fake


# -- router-side tracing + probe tagging + fleet SLO (ISSUE 14) --------------


class TestRouterTracing:
    def _app(self, fakes, obs=None, **kw):
        kw.setdefault("health_interval_s", 3600)
        app = FleetApp([f.url for f in fakes], obs=obs or _Obs(), **kw)
        app._sweep()
        return app

    def test_minted_id_forwarded_and_fleet_trace_emitted(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        body, hdrs = _segment_body()
        status, data, headers = app.proxy_segment(body, hdrs)
        assert status == 200
        recs = obs.events.of("fleet_trace")
        assert len(recs) == 1
        rec = recs[0]
        assert rec["status"] == 200 and rec["replica_hops"] == 0
        assert rec["request_id"].startswith("fl-")
        names = [s["name"] for s in rec["spans"]]
        assert names == ["route_pick", "proxy_hop"]
        hop = rec["spans"][1]
        assert hop["outcome"] == "ok" and hop["replica"] == rec["replica"]
        # the minted id went replica-ward: the serving fake saw it
        served = a.requests + b.requests
        assert served and served[-1]["id"] == rec["trace_id"]
        # the SLO status class landed
        assert obs.registry.get(
            "fleet_requests_total", status="ok"
        ).value == 1
        assert obs.registry.get("fleet_request_seconds").count == 1

    def test_client_probe_header_is_stripped(self, two_fakes):
        """A client smuggling X-Nm03-Probe through the fleet must NOT get
        its traffic excluded from the replica's request metrics — only
        the router's own canary path may set the tag (review fix)."""
        a, b = two_fakes
        app = self._app([a, b])
        body, hdrs = _segment_body()
        status, _, _ = app.proxy_segment(
            body, {**hdrs, "X-Nm03-Probe": "1"}
        )
        assert status == 200
        served = (a.requests + b.requests)[-1]
        assert served["probe"] is None  # stripped before the forward

    def test_honored_client_id_shared_with_replica(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        body, hdrs = _segment_body()
        status, _, _ = app.proxy_segment(
            body, {**hdrs, "x-nm03-request-id": "ignored-case-variant"},
            trace_id="client-42",
        )
        assert status == 200
        rec = obs.events.of("fleet_trace")[0]
        assert rec["trace_id"] == "client-42"
        served = a.requests + b.requests
        # the canonical id replaced any case variant of the client's
        assert served[-1]["id"] == "client-42"

    def test_failover_chain_in_spans(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        a.drop = True
        body, hdrs = _segment_body()
        status, data, _ = app.proxy_segment(body, hdrs)
        assert status == 200
        rec = obs.events.of("fleet_trace")[0]
        names = [s["name"] for s in rec["spans"]]
        # the acceptance chain: pick -> hop(A, died) -> failover -> pick
        # -> hop(B, ok), one trace id throughout
        assert names == [
            "route_pick", "proxy_hop", "failover", "route_pick", "proxy_hop",
        ]
        hops = [s for s in rec["spans"] if s["name"] == "proxy_hop"]
        assert hops[0]["outcome"] == "io_error"
        assert hops[0]["replica"] == a.label
        assert hops[1]["outcome"] == "ok" and hops[1]["replica"] == b.label
        assert {s["trace_ids"][0] for s in rec["spans"]} == {rec["trace_id"]}
        fail = next(s for s in rec["spans"] if s["name"] == "failover")
        assert fail["cause"] == "io_error" and fail["replica"] == a.label
        assert rec["replica_hops"] == 1 and rec["replica"] == b.label

    def test_fleet_wide_shed_is_traced_and_echoed(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        a.shed = b.shed = True
        body, hdrs = _segment_body()
        status, _, headers = app.proxy_segment(
            body, hdrs, trace_id="shed-1"
        )
        assert status == 503
        assert dict(headers)["X-Nm03-Request-Id"] == "shed-1"
        rec = obs.events.of("fleet_trace")[0]
        assert rec["status"] == 503 and rec["replica"] is None
        hops = [s for s in rec["spans"] if s["name"] == "proxy_hop"]
        assert len(hops) == 2
        assert {h["outcome"] for h in hops} == {"shed"}
        assert obs.registry.get(
            "fleet_requests_total", status="shed"
        ).value == 1

    def test_application_4xx_counts_invalid(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        body = b"\xff" + bytes(1023)  # the fakes 400 this
        status, _, _ = app.proxy_segment(body, _segment_body()[1])
        assert status == 400
        assert obs.registry.get(
            "fleet_requests_total", status="invalid"
        ).value == 1
        rec = obs.events.of("fleet_trace")[0]
        assert rec["spans"][-1]["outcome"] == "http_400"

    def test_request_classes_exist_at_zero_from_startup(self, two_fakes):
        obs = _Obs()
        self._app(list(two_fakes), obs)
        for cls in ("ok", "error", "shed"):
            m = obs.registry.get("fleet_requests_total", status=cls)
            assert m is not None and m.value == 0

    def test_canary_probe_tagged_and_traced(self, two_fakes):
        a, b = two_fakes
        obs = _Obs()
        app = self._app([a, b], obs)
        app.replicas.eject(b.url, "refused")
        app._probe_one(b.url, 7)  # the canary, synchronously
        assert app.replicas.state(b.url) == HEALTHY
        # the replica saw the probe TAG — the metrics-exclusion satellite
        probe_req = b.requests[-1]
        assert probe_req["probe"] == "1"
        assert probe_req["id"].startswith("fleet-probe-")
        recs = [r for r in obs.events.of("fleet_trace") if r.get("probe")]
        assert len(recs) == 1
        span = recs[0]["spans"][0]
        assert span["name"] == "canary_probe"
        assert span["outcome"] == "passed" and span["replica"] == b.label
        # probes never count as fleet requests
        assert obs.registry.get(
            "fleet_requests_total", status="ok"
        ).value == 0

    def test_clock_offset_recorded_from_handshake(self, two_fakes):
        a, b = two_fakes
        app = self._app([a, b])
        # the fakes publish clock {mono_s: 5, ts_unix: 1005} -> offset 1000
        assert app.replicas.signals(a.url)["clock_offset_s"] == 1000.0
        snap = app.status()["replicas"]["per_replica"]
        assert all(r["clock_offset_s"] == 1000.0 for r in snap)


class TestFleetSLO:
    def test_burn_gauges_and_readyz_block(self, two_fakes):
        from nm03_capstone_project_tpu.obs.slo import SLOObjective

        a, b = two_fakes
        obs = _Obs()
        app = FleetApp(
            [a.url, b.url], obs=obs, health_interval_s=3600,
            slo=SLOObjective(99.0, latency_target_s=30.0,
                             window_fast_s=30.0, window_slow_s=600.0),
        )
        app._sweep()
        body, hdrs = _segment_body()
        for _ in range(4):
            assert app.proxy_segment(body, hdrs)[0] == 200
        app.publish_gauges()
        assert obs.registry.get("slo_burn_rate_fast").value == 0.0
        assert obs.registry.get("slo_error_budget_remaining").value == 1.0
        st = app.status()
        assert st["slo"]["objective"]["availability_pct"] == 99.0
        assert st["slo"]["error_budget_remaining"] == 1.0
        # now burn: every replica sheds -> fleet-wide 503s are bad
        a.shed = b.shed = True
        for _ in range(4):
            assert app.proxy_segment(body, hdrs)[0] == 503
        block = app.slo.publish()
        assert block["burn_rate_fast"] > 1.0
        assert block["error_budget_remaining"] < 1.0

    def test_no_objective_no_gauges(self, two_fakes):
        obs = _Obs()
        app = FleetApp(
            [f.url for f in two_fakes], obs=obs, health_interval_s=3600,
        )
        app._sweep()
        app.publish_gauges()
        assert obs.registry.get("slo_burn_rate_fast") is None
        assert app.status()["slo"] is None


# -- nm03-top --fleet rendering (canned payloads, ISSUE 14 satellite) --------


class TestFleetTopRender:
    """The ISSUE 13 console path had no direct render test: canned fleet
    /metrics.json + /readyz payloads -> build_fleet_view/render_fleet_text,
    including the SLO row."""

    def _fleet_sample(self, ts=100.0, routed=40.0, with_slo=True):
        from nm03_capstone_project_tpu.serving.top import Sample

        metrics = [
            {"name": "fleet_requests_routed_total", "type": "counter",
             "labels": {"replica": "127.0.0.1:8081"}, "value": routed},
            {"name": "fleet_failovers_total", "type": "counter",
             "labels": {"replica": "127.0.0.1:8082", "cause": "io_error"},
             "value": 2.0},
            {"name": "fleet_shed_total", "type": "counter", "labels": {},
             "value": 0.0},
        ]
        if with_slo:
            metrics += [
                {"name": "slo_burn_rate_fast", "type": "gauge",
                 "labels": {}, "value": 0.25},
                {"name": "slo_burn_rate_slow", "type": "gauge",
                 "labels": {}, "value": 0.1},
                {"name": "slo_error_budget_remaining", "type": "gauge",
                 "labels": {}, "value": 0.9},
            ]
        readyz = {
            "ready": True, "draining": False, "capacity": 0.833,
            "uptime_s": 12.5,
            "replicas": {
                "count": 2, "ready": 2, "ejected": 0,
                "per_replica": [
                    {"target": "http://127.0.0.1:8081",
                     "replica": "127.0.0.1:8081", "state": "healthy",
                     "cause": None, "ejections": 0, "capacity": 1.0,
                     "identity": {"id": "aaa", "pid": 11}},
                    {"target": "http://127.0.0.1:8082",
                     "replica": "127.0.0.1:8082", "state": "ejected",
                     "cause": "refused", "ejections": 2, "capacity": 0.667,
                     "identity": {"id": "bbb", "pid": 22}},
                ],
            },
        }
        return Sample({"metrics": metrics}, readyz, ts)

    def _replica_sample(self, ts=100.0, requests=10.0):
        from nm03_capstone_project_tpu.serving.top import Sample

        metrics = [
            {"name": "serving_busy_fraction", "type": "gauge", "labels": {},
             "value": 0.42},
            {"name": "serving_mfu", "type": "gauge", "labels": {},
             "value": 0.001},
            {"name": "serving_requests_total", "type": "counter",
             "labels": {"status": "ok"}, "value": requests},
        ]
        readyz = {"queue_depth": 3, "lanes": {"ready": 4}}
        return Sample({"metrics": metrics}, readyz, ts)

    def test_build_fleet_view_rows_and_slo(self):
        from nm03_capstone_project_tpu.serving.top import build_fleet_view

        fleet = self._fleet_sample()
        per = {
            "http://127.0.0.1:8081": self._replica_sample(),
            "http://127.0.0.1:8082": None,  # dead replica -> null row
        }
        view = build_fleet_view(fleet, per)
        assert view["schema"] == "nm03.fleettop.v1"
        assert view["replicas_ready"] == 2 and len(view["replicas"]) == 2
        live, dead = view["replicas"]
        assert live["replica"] == "127.0.0.1:8081"
        assert live["busy_fraction"] == 0.42
        assert live["lanes_ready"] == 4 and live["queue_depth"] == 3
        assert dead["state"] == "ejected" and dead["busy_fraction"] is None
        assert view["slo"] == {
            "error_budget_remaining": 0.9,
            "burn_rate_fast": 0.25,
            "burn_rate_slow": 0.1,
        }

    def test_rates_from_counter_deltas(self):
        from nm03_capstone_project_tpu.serving.top import build_fleet_view

        prev_fleet = self._fleet_sample(ts=100.0, routed=40.0)
        cur_fleet = self._fleet_sample(ts=110.0, routed=60.0)
        prev_per = {"http://127.0.0.1:8081": self._replica_sample(100.0, 10)}
        cur_per = {"http://127.0.0.1:8081": self._replica_sample(110.0, 30)}
        view = build_fleet_view(cur_fleet, cur_per, prev_fleet, prev_per)
        assert view["rates_per_s"]["routed"] == pytest.approx(2.0)
        assert view["replicas"][0]["requests_per_s"] == pytest.approx(2.0)

    def test_render_text_carries_rows_and_slo_line(self):
        from nm03_capstone_project_tpu.serving.top import (
            build_fleet_view,
            render_fleet_text,
        )

        view = build_fleet_view(
            self._fleet_sample(),
            {"http://127.0.0.1:8081": self._replica_sample()},
        )
        screen = render_fleet_text(view, "http://fleet:8070")
        assert "127.0.0.1:8081" in screen and "127.0.0.1:8082" in screen
        assert "ejected" in screen
        assert "slo burn fast 0.25" in screen
        assert "slow 0.1" in screen and "budget 90% left" in screen
        # the replica row carries its live busy fraction
        assert "42%" in screen

    def test_no_slo_no_row(self):
        from nm03_capstone_project_tpu.serving.top import (
            build_fleet_view,
            render_fleet_text,
        )

        view = build_fleet_view(self._fleet_sample(with_slo=False), {})
        assert view["slo"] is None
        assert "slo burn" not in render_fleet_text(view, "u")


# -- the fleet fault site ----------------------------------------------------


class TestFleetFaultSite:
    def test_kinds_are_validated(self):
        with pytest.raises(ValueError):
            FaultPlan.from_spec(
                {"faults": [{"site": "fleet", "kind": "bogus"}]}
            )
        plan = FaultPlan.from_spec({"faults": [
            {"site": "fleet", "kind": "replica_unreachable", "stem": "h:1"},
            {"site": "fleet", "kind": "proxy_io_error", "index": 2},
        ]})
        assert plan.has_site("fleet")

    def test_kinds_filter_isolates_budgets(self):
        """The new fire(kinds=...) contract: a proxy_io_error rule must
        not fire at — or spend its count budget on — a health-poll check
        that only consults replica_unreachable rules."""
        plan = FaultPlan.from_spec({"faults": [
            {"site": "fleet", "kind": "proxy_io_error", "count": 1},
        ]})
        # ten health-poll-shaped checks: skipped entirely, budget intact
        for _ in range(10):
            assert plan.fire(
                "fleet", stem="h:1", kinds=("replica_unreachable",)
            ) is None
        hit = plan.fire("fleet", stem="h:1", index=1, kinds=("proxy_io_error",))
        assert hit is not None and hit.kind == "proxy_io_error"
        assert plan.fire(
            "fleet", stem="h:1", index=2, kinds=("proxy_io_error",)
        ) is None  # count=1 spent on the real proxy check, not the polls

    def test_replica_unreachable_drill(self, two_fakes):
        """Deterministic ejection: the health poll for ONE chosen replica
        behaves as refused for `count` polls, then the replica heals
        through probation — no process was harmed."""
        a, b = two_fakes
        obs = _Obs()
        plan = FaultPlan.from_spec({"faults": [{
            "site": "fleet", "kind": "replica_unreachable",
            "stem": a.label, "count": 2,
        }]})
        app = FleetApp(
            [a.url, b.url], obs=obs, fault_plan=plan,
            health_interval_s=3600, probe_interval_s=0.0, canary_timeout_s=5.0,
        )
        app._sweep()  # poll 1: injected refusal -> ejected
        assert app.replicas.state(a.url) == EJECTED
        assert app.replicas.cause(a.url) == "refused"
        assert app.replicas.state(b.url) == HEALTHY
        app._sweep()  # poll 2: still injected -> stays out (idempotent)
        assert app.replicas.state(a.url) == EJECTED
        app._sweep()  # budget spent: poll passes -> canary -> reinstated
        assert _wait_state(app, a.url, HEALTHY)
        assert len(obs.faults) == 2
        assert all(f["kind"] == "replica_unreachable" for f in obs.faults)

    def test_proxy_io_error_drill(self, two_fakes):
        """Deterministic failover: one proxied request aborts mid-body;
        the rider lands on the other replica with hops=1 and the fault
        is counted."""
        a, b = two_fakes
        obs = _Obs()
        plan = FaultPlan.from_spec({"faults": [{
            "site": "fleet", "kind": "proxy_io_error", "count": 1,
        }]})
        app = FleetApp(
            [a.url, b.url], obs=obs, fault_plan=plan,
            health_interval_s=3600,
        )
        app._sweep()
        body, hdrs = _segment_body()
        status, data, _ = app.proxy_segment(body, hdrs)
        assert status == 200
        p = json.loads(data)
        assert p["replica_hops"] == 1
        assert obs.registry.get(
            "fleet_failovers_total",
            replica=target_label(
                a.url if p["replica"] == b.label else b.url
            ),
            cause="io_error",
        ).value == 1
        assert [f["kind"] for f in obs.faults] == ["proxy_io_error"]
        # budget spent: the next request routes clean
        status, data, _ = app.proxy_segment(body, hdrs)
        assert json.loads(data)["replica_hops"] == 0


# -- loadgen --targets + the fleet gates -------------------------------------


class TestLoadgenMultiTarget:
    def test_run_load_spreads_over_urls_and_records_attribution(
        self, two_fakes
    ):
        from nm03_capstone_project_tpu.serving.loadgen import (
            LoadResult,
            run_load,
        )

        a, b = two_fakes
        result = LoadResult()
        body_urls = [f"{a.url}/v1/segment", f"{b.url}/v1/segment"]
        summary = run_load(
            body_urls, [(_segment_body()[0], _segment_body()[1])],
            n_requests=8, concurrency=2, rate_rps=0.0, timeout_s=10.0,
            result=result,
        )
        assert summary["requests_ok"] == 8
        assert len(a.requests) == 4 and len(b.requests) == 4
        # no fleet in front: attribution falls back to the TARGET's
        # host:port, so a direct multi-replica run still shows its spread
        assert summary["replicas_observed"] == {a.label: 4, b.label: 4}
        assert summary["failovers_observed"] == 0

    def test_loadgen_reads_fleet_truth_fields(self, two_fakes):
        from nm03_capstone_project_tpu.serving.loadgen import (
            LoadResult,
            run_load,
        )

        a, b = two_fakes
        app = FleetApp([a.url, b.url], obs=_Obs(), health_interval_s=3600)
        httpd, _, port = serve_in_thread(app)
        try:
            a.drop = True  # first hit on a fails over: hops=1 for a rider
            result = LoadResult()
            summary = run_load(
                f"http://127.0.0.1:{port}/v1/segment",
                [(_segment_body()[0], _segment_body()[1])],
                n_requests=6, concurrency=2, rate_rps=0.0, timeout_s=10.0,
                result=result,
            )
            assert summary["requests_ok"] == 6
            assert set(summary["replicas_observed"]) <= {a.label, b.label}
            assert b.label in summary["replicas_observed"]
            assert summary["failovers_observed"] >= 1
            hops = [r.get("replica_hops") for r in result.requests]
            assert any(h and h >= 1 for h in hops)
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_capacity_watch_tracks_fleet_floor(self, two_fakes):
        from nm03_capstone_project_tpu.serving.loadgen import (
            CapacityWatch,
            probe_server_topology,
        )

        a, b = two_fakes
        app = FleetApp([a.url, b.url], obs=_Obs(), health_interval_s=3600)
        httpd, _, port = serve_in_thread(app)
        base = f"http://127.0.0.1:{port}"
        try:
            topo = probe_server_topology(base)
            assert topo["is_fleet"] and topo["replicas"] == 2
            assert topo["capacity"] == 1.0
            watch = CapacityWatch(base, interval_s=0.05).start()
            time.sleep(0.12)
            app.replicas.eject(a.url, "refused")
            time.sleep(0.2)
            watch.stop()
            assert watch.min_fleet_capacity == 0.5
            assert watch.max_replicas_ejected == 1
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestFleetTelemetryGates:
    """The check_telemetry fleet-gate battery: labeled selectors whose
    replica values carry ':' (host:port) — red and green."""

    def _snapshot(self, tmp_path):
        snap = {
            "schema": "nm03.metrics.v1", "run_id": "r", "git_sha": "g",
            "created_unix": 1.0,
            "metrics": [
                {"name": "fleet_replica_state", "type": "gauge",
                 "labels": {"replica": "127.0.0.1:8081"}, "value": 0},
                {"name": "fleet_replica_state", "type": "gauge",
                 "labels": {"replica": "127.0.0.1:8082"}, "value": 2},
                {"name": "fleet_failovers_total", "type": "counter",
                 "labels": {"replica": "127.0.0.1:8082",
                            "cause": "io_error"}, "value": 2},
                {"name": "fleet_shed_total", "type": "counter",
                 "labels": {}, "value": 0},
                {"name": "fleet_routed_capacity", "type": "gauge",
                 "labels": {}, "value": 0.667},
            ],
        }
        p = tmp_path / "m.json"
        p.write_text(json.dumps(snap))
        return p

    def _run(self, p, *args):
        return subprocess.run(
            [sys.executable, CHECKER, "--metrics", str(p), *args],
            capture_output=True, text=True, timeout=60,
        )

    def test_green_gates(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(
            p,
            "--expect-gauge", "fleet_replica_state{replica=127.0.0.1:8081}=0",
            "--expect-gauge", "fleet_replica_state{replica=127.0.0.1:8082}=2",
            "--expect-counter", "fleet_failovers_total=1",
            "--expect-counter",
            "fleet_failovers_total{replica=127.0.0.1:8082,cause=io_error}=2",
            "--expect-counter", "fleet_shed_total==0",
            "--expect-gauge-range", "fleet_routed_capacity=(0..1]",
        )
        assert r.returncode == 0, r.stderr

    def test_unhealed_replica_red(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(
            p, "--expect-gauge",
            "fleet_replica_state{replica=127.0.0.1:8082}=0",
        )
        assert r.returncode == 1 and "expected == 0" in r.stderr

    def test_never_reported_replica_red(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(
            p, "--expect-gauge",
            "fleet_replica_state{replica=127.0.0.1:9999}=0",
        )
        assert r.returncode == 1 and "no series matches" in r.stderr

    def test_missing_failovers_red(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(
            p, "--expect-counter",
            "fleet_failovers_total{replica=127.0.0.1:8081}=1",
        )
        assert r.returncode == 1 and "no series matches" in r.stderr

    def test_capacity_range_red(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(
            p, "--expect-gauge-range", "fleet_routed_capacity=(0.9..1]",
        )
        assert r.returncode == 1 and "expected in" in r.stderr


# -- rolling restart (dummy replicas) ---------------------------------------


_DUMMY = """
import json, os, signal, sys
from http.server import BaseHTTPRequestHandler, HTTPServer

port, gen = int(sys.argv[1]), int(sys.argv[2])
script = os.path.abspath(__file__)

class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def log_message(self, *a): pass
    def do_GET(self):
        body = json.dumps({
            "ready": True, "capacity": 1.0,
            "queue_depth": 0, "queue_capacity": 8,
            "replica": {
                "id": f"gen{gen}-{os.getpid()}", "pid": os.getpid(),
                "start_unix": 0.0,
                "relaunch_argv": [sys.executable, script, str(port),
                                  str(gen + 1)],
                "cwd": os.getcwd(),
            },
            "compile_hub": {"builds": 0, "cache_hits": 1},
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

srv = HTTPServer(("127.0.0.1", port), H)
signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
print("ready", flush=True)
srv.serve_forever()
"""


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_http(url, timeout_s=30):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                r.read()
                return True
        except Exception:  # noqa: BLE001
            time.sleep(0.1)
    return False


class TestRollingRestart:
    def test_rolls_through_dummies_one_at_a_time(self, tmp_path):
        from nm03_capstone_project_tpu.fleet.manager import rolling_restart

        script = tmp_path / "dummy.py"
        script.write_text(_DUMMY)
        ports = _free_ports(2)
        procs = [
            subprocess.Popen([sys.executable, str(script), str(p), "1"])
            for p in ports
        ]
        spawned = []

        def spawn(argv, **kw):
            kw.pop("stdout", None)
            kw.pop("stderr", None)
            kw.pop("start_new_session", None)
            proc = subprocess.Popen(argv, **kw)
            spawned.append(proc)
            return proc

        try:
            targets = [f"127.0.0.1:{p}" for p in ports]
            for p in ports:
                assert _wait_http(f"http://127.0.0.1:{p}/readyz")
            report = rolling_restart(
                targets, drain_timeout_s=30, warm_timeout_s=30,
                poll_s=0.05, spawn=spawn, emit=lambda m: None,
            )
            assert report["ok"] is True
            assert len(report["replicas"]) == 2
            old_pids = [p.pid for p in procs]
            for entry, old in zip(report["replicas"], old_pids):
                assert entry["ok"] and entry["old_pid"] == old
                assert entry["new_pid"] != old
                assert entry["builds"] == 0 and entry["cache_hits"] == 1
                assert entry["new_id"].startswith("gen2-")
            # the originals really died, the spawns really live
            for p in procs:
                assert p.wait(timeout=10) == 0
            for p in ports:
                _, st = _readyz(f"http://127.0.0.1:{p}")
                assert st["replica"]["id"].startswith("gen2-")
        finally:
            for p in procs + spawned:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)

    def test_relaunch_recipe_substitutes_the_bound_port(self):
        """The /readyz relaunch recipe must be reproducible: an ephemeral
        `--port 0` republished verbatim would relaunch the replica on a
        DIFFERENT random port and the orchestrator's warm-wait against
        the old address could never succeed."""
        from nm03_capstone_project_tpu.serving.server import _relaunch_recipe

        rec = _relaunch_recipe(["--port", "0", "--lanes", "2"], 18081)
        assert rec[:3] == [
            sys.executable, "-m",
            "nm03_capstone_project_tpu.serving.server",
        ]
        assert rec[3:] == ["--port", "18081", "--lanes", "2"]
        # --port=0 spelling
        assert "--port=18081" in _relaunch_recipe(["--port=0"], 18081)
        # defaulted port becomes explicit — the recipe stands alone
        assert _relaunch_recipe(["--lanes", "1"], 8077)[3:] == [
            "--lanes", "1", "--port", "8077",
        ]

    def test_compile_cache_dir_is_ensured_on_relaunch(self):
        from nm03_capstone_project_tpu.fleet.manager import _relaunch_argv

        argv = ["python", "-m", "x", "--port", "1"]
        out = _relaunch_argv(argv, "/tmp/cache")
        assert out[-2:] == ["--compile-cache-dir", "/tmp/cache"]
        argv2 = ["python", "-m", "x", "--compile-cache-dir", "/old"]
        out2 = _relaunch_argv(argv2, "/new")
        assert out2 == ["python", "-m", "x", "--compile-cache-dir", "/new"]
        assert _relaunch_argv(argv, None) == argv

    def test_restart_refuses_identityless_replica(self, two_fakes):
        """A replica whose /readyz has no relaunch recipe (an embedded
        ServingApp, an old build) stops the walk with a clear error —
        never a blind SIGTERM of a pid it cannot bring back."""
        from nm03_capstone_project_tpu.fleet.manager import (
            RestartError,
            rolling_restart,
        )

        a, _ = two_fakes  # FakeReplica reports id/pid but no relaunch_argv
        with pytest.raises(RestartError, match="relaunch_argv"):
            rolling_restart([a.url], emit=lambda m: None)


def _readyz(url, timeout=5.0):
    req = urllib.request.Request(f"{url}/readyz", method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


# -- subprocess acceptance drills (real nm03-serve replicas) -----------------


def _spawn_replica(port, tmp_path, tag, extra=(), env=None):
    """One real nm03-serve replica on a fixed port; returns (proc, url)."""
    proc = subprocess.Popen(
        [
            sys.executable, "-m",
            "nm03_capstone_project_tpu.serving.server",
            "--device", "cpu", "--port", str(port),
            "--canvas", str(CANVAS), "--buckets", "1", "--lanes", "1",
            "--max-wait-ms", "10", "--heartbeat-s", "0",
            "--queue-capacity", "64",
            *extra,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )
    return proc, f"http://127.0.0.1:{port}"


def _cpu_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    return env


def _wait_replicas_ready(procs_urls, timeout_s=300):
    deadline = time.monotonic() + timeout_s
    pending = {u for _, u in procs_urls}
    while pending and time.monotonic() < deadline:
        for proc, url in procs_urls:
            if url not in pending:
                continue
            if proc.poll() is not None:
                pytest.fail(f"replica {url} died: {proc.stdout.read()}")
            try:
                status, st = _readyz(url, timeout=2.0)
                if status == 200 and st.get("ready"):
                    pending.discard(url)
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.2)
    assert not pending, f"replicas never ready: {pending}"


def _expected_mask_pixels(img) -> int:
    """The single-replica reference mask for one slice (in-process)."""
    import jax.numpy as jnp
    import numpy as np

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

    out = process_slice(
        jnp.asarray(img.astype(np.float32)),
        jnp.asarray([img.shape[0], img.shape[1]], jnp.int32),
        PipelineConfig(canvas=CANVAS),
    )
    return int(np.count_nonzero(np.asarray(out["mask"])))


def _post(url, body, headers, timeout=120.0):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class _FleetReadyzPoller:
    """Samples the fleet /readyz through a drill: statuses + payloads."""

    def __init__(self, base):
        self.base = base
        self.samples = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(0.1):
            try:
                status, st = _readyz(self.base, timeout=5.0)
                with self._lock:
                    self.samples.append((status, st))
            except Exception:  # noqa: BLE001 — transient socket noise
                pass

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
        with self._lock:
            return list(self.samples)


class TestFleetChaosAcceptanceDrill:
    def test_sigkill_one_replica_mid_run_zero_failed_requests(self, tmp_path):
        """The ISSUE 13 acceptance bar, end to end with real processes:
        nm03-fleet over three nm03-serve replicas under a 32-req/8-way
        nm03-loadgen --targets run; SIGKILL one replica mid-run — zero
        failed client requests (in-flight riders fail over; masks
        bit-identical to a single replica's), fleet /readyz never leaves
        200 with the ⅔-capacity plateau observed live; restarting the
        replica reinstates it to 3/3 through probation; gated by the
        labeled fleet metrics via check_telemetry."""
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice
        from nm03_capstone_project_tpu.serving import loadgen

        env = _cpu_env()
        ports = _free_ports(4)
        victim_port = ports[2]
        victim_label = f"127.0.0.1:{victim_port}"
        # the victim's first dispatch hangs (long deadline: no lane
        # quarantine) so requests are parked in-flight on it when the
        # SIGKILL lands — the deterministic "dying replica" window
        hang_plan = json.dumps({"seed": 3, "faults": [{
            "site": "dispatch", "kind": "hang", "count": 1, "hang_s": 120.0,
        }]})
        replicas = []
        replica_logs = []
        for i, port in enumerate(ports[:3]):
            # every replica writes its own event stream (ISSUE 14): the
            # multi-log merge stitches them — the victim's torn,
            # SIGKILLed log included — into one fleet timeline
            log_path = tmp_path / f"r{i}_events.jsonl"
            replica_logs.append(log_path)
            extra = ["--request-timeout-s", "300",
                     "--log-json", str(log_path)]
            if port == victim_port:
                extra += ["--fault-plan", hang_plan,
                          "--dispatch-timeout-s", "240"]
            replicas.append(_spawn_replica(port, tmp_path, i, extra, env))
        fleet_metrics = tmp_path / "fleet_metrics.json"
        fleet_events = tmp_path / "fleet_events.jsonl"
        fleet_proc = None
        poller = None
        relaunched = None
        try:
            _wait_replicas_ready(replicas)
            targets = ",".join(f"127.0.0.1:{p}" for p in ports[:3])
            fleet_proc = subprocess.Popen(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.fleet.cli", "serve",
                    "--replicas", targets,
                    "--port", str(ports[3]),
                    "--health-interval-s", "0.25",
                    "--probe-interval-s", "0.5",
                    "--health-timeout-s", "2.0",
                    "--proxy-timeout-s", "240",
                    "--canary-hw", "32",
                    "--metrics-out", str(fleet_metrics),
                    "--log-json", str(fleet_events),
                    # the declared SLO (ISSUE 14): zero failed client
                    # requests is the drill's bar, so the budget must
                    # survive intact — gated below on the snapshot
                    "--slo-availability", "99.0",
                    "--slo-p99-ms", "300000",
                    "--slo-fast-window-s", "60",
                    "--slo-slow-window-s", "600",
                ],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env, cwd=REPO,
            )
            fleet_url = f"http://127.0.0.1:{ports[3]}"
            assert _wait_http(f"{fleet_url}/readyz", 60), "fleet never up"
            status, st = _readyz(fleet_url)
            assert status == 200 and st["replicas"]["ready"] == 3
            # reference mask from ONE replica directly (single-replica
            # truth the fleet-served masks must be bit-identical to)
            img = phantom_slice(CANVAS, CANVAS, seed=1)
            want = _expected_mask_pixels(img)
            body = img.astype("<f4").tobytes()
            hdrs = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Height": str(CANVAS), "X-Nm03-Width": str(CANVAS),
            }
            s, p = _post(replicas[0][1] + "/v1/segment?output=mask", body, hdrs)
            assert s == 200 and p["mask_pixels"] == want

            poller = _FleetReadyzPoller(fleet_url).start()
            results_json = tmp_path / "loadgen.json"
            lg_rc = []

            def run_loadgen():
                lg_rc.append(loadgen.main([
                    "--targets", fleet_url,
                    "--requests", "32", "--concurrency", "8",
                    "--timeout-s", "240", "--warmup", "0",
                    "--height", str(CANVAS), "--width", str(CANVAS),
                    "--results-json", str(results_json),
                    # the client-side SLO gate (ISSUE 14): the kill must
                    # not cost availability; failing it fails main()
                    "--expect-slo", "availability=99.0,p99_ms=240000",
                ]))

            lg = threading.Thread(target=run_loadgen, daemon=True)
            lg.start()
            # SIGKILL the victim once riders are parked on its hung lane
            victim_proc = replicas[2][0]
            victim_url = replicas[2][1]
            deadline = time.monotonic() + 60
            parked = False
            while time.monotonic() < deadline and not parked:
                try:
                    with urllib.request.urlopen(
                        f"{victim_url}/metrics.json", timeout=2
                    ) as r:
                        snap = json.loads(r.read())
                    for m in snap.get("metrics", []):
                        if (m["name"] == "serving_inflight"
                                and m.get("value", 0) >= 1):
                            parked = True
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.05)
            assert parked, "no rider ever parked on the victim"
            victim_proc.kill()
            victim_proc.wait(timeout=30)
            lg.join(timeout=300)
            assert lg_rc == [0]
            summary = json.loads(results_json.read_text())
            # THE bar: zero failed client requests through the kill
            assert summary["statuses"] == {"ok": 32}, summary["statuses"]
            # the client-side SLO verdict rides the artifact (ISSUE 14)
            assert summary["slo_gate"]["pass"] is True, summary["slo_gate"]
            assert summary["failovers_observed"] >= 1, summary
            assert set(summary["replicas_observed"]) <= {
                f"127.0.0.1:{p}" for p in ports[:3]
            }
            surviving = {f"127.0.0.1:{p}" for p in (ports[0], ports[1])}
            assert surviving <= set(summary["replicas_observed"]), summary
            # the ⅔ plateau, observed live by the loadgen capacity watch
            assert summary["fleet_capacity_min_observed"] is not None
            assert summary["fleet_capacity_min_observed"] <= 2 / 3 + 1e-6
            assert summary["replicas_ejected_max_observed"] >= 1
            # masks through the fleet are bit-identical to single-replica
            wave = [
                _post(fleet_url + "/v1/segment?output=mask", body, hdrs)
                for _ in range(4)
            ]
            assert all(s == 200 and p["mask_pixels"] == want for s, p in wave)
            # restart the victim (no fault plan: the hang was its outage)
            relaunched, _ = _spawn_replica(
                victim_port, tmp_path, "revived",
                ["--request-timeout-s", "300"], env,
            )
            deadline = time.monotonic() + 300
            healed = False
            while time.monotonic() < deadline and not healed:
                status, st = _readyz(fleet_url)
                healed = (
                    status == 200 and st["replicas"]["ready"] == 3
                    and st["capacity"] == 1.0
                )
                time.sleep(0.2)
            assert healed, st
            samples = poller.stop()
            poller = None
            # fleet /readyz NEVER left 200, and the plateau was visible
            assert samples, "no /readyz samples"
            assert {s for s, _ in samples} == {200}
            dips = [
                st for _, st in samples
                if st.get("replicas", {}).get("ejected", 0) >= 1
            ]
            assert dips, "ejection window never observed on fleet /readyz"
            assert any(
                abs(st["capacity"] - 2 / 3) < 1e-3 for st in dips
            ), sorted({st["capacity"] for st in dips})
            # nm03-top --fleet aggregates the healed fleet in one view
            top = subprocess.run(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.serving.top",
                    "--fleet", "--url", fleet_url,
                    "--once", "--format", "json",
                ],
                capture_output=True, text=True, timeout=120, env=env,
                cwd=REPO,
            )
            assert top.returncode == 0, top.stderr
            view = json.loads(top.stdout)
            assert view["schema"] == "nm03.fleettop.v1"
            assert view["replicas_ready"] == 3
            assert len(view["replicas"]) == 3
            assert all(r["state"] == "healthy" for r in view["replicas"])
            assert any(r["busy_fraction"] is not None
                       for r in view["replicas"])
            # drain the fleet; its snapshot carries the labeled evidence
            fleet_proc.send_signal(signal.SIGTERM)
            out, _ = fleet_proc.communicate(timeout=120)
            assert fleet_proc.returncode == 0, out
            res = subprocess.run(
                [
                    sys.executable, CHECKER,
                    "--metrics", str(fleet_metrics),
                    "--events", str(fleet_events),
                    "--expect-gauge", "fleet_replicas_ready=3",
                    "--expect-gauge",
                    f"fleet_replica_state{{replica={victim_label}}}=0",
                    "--expect-counter",
                    f"fleet_replica_ejections_total{{replica={victim_label}}}=1",
                    "--expect-counter",
                    f"fleet_replica_reinstated_total{{replica={victim_label}}}=1",
                    "--expect-counter", "fleet_failovers_total=1",
                    "--expect-counter", "fleet_shed_total==0",
                    "--expect-gauge-range", "fleet_routed_capacity=(0..1]",
                    # the SLO plane's verdict on the same run (ISSUE 14):
                    # zero failed requests = nothing burned, budget intact
                    "--expect-gauge-range", "slo_burn_rate_fast=[0..1)",
                    "--expect-gauge-range", "slo_burn_rate_slow=[0..1)",
                    "--expect-gauge-range",
                    "slo_error_budget_remaining=(0.5..1]",
                    "--expect-counter", "fleet_requests_total{status=ok}=32",
                ],
                capture_output=True, text=True, timeout=60,
            )
            assert res.returncode == 0, res.stderr
            # ONE merged timeline across the whole fleet (ISSUE 14): the
            # router's log plus every replica's — the SIGKILLed victim's
            # torn stream included — validated by --expect-fleet-trace:
            # every proxy_hop trace id resolves to a replica-side span
            # tree, and the failed-over request's chain is visible
            merged = tmp_path / "fleet.trace.json"
            res = subprocess.run(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.obs.trace",
                    str(fleet_events), *[str(p) for p in replica_logs],
                    "-o", str(merged),
                ],
                capture_output=True, text=True, timeout=120, cwd=REPO,
            )
            assert res.returncode == 0, res.stderr + res.stdout
            res = subprocess.run(
                [sys.executable, CHECKER,
                 "--expect-fleet-trace", str(merged)],
                capture_output=True, text=True, timeout=60,
            )
            assert res.returncode == 0, res.stderr
            events = json.loads(merged.read_text())["traceEvents"]
            b_events = [e for e in events if e.get("ph") == "B"]
            # the acceptance chain is in the artifact: a proxy_hop that
            # DIED on the victim, a failover span, and the same trace id
            # answered by a surviving replica's span tree
            died = [
                e for e in b_events
                if e["name"] == "proxy_hop"
                and e["args"].get("replica") == victim_label
                and e["args"].get("outcome") == "io_error"
            ]
            assert died, "no io_error proxy_hop on the killed replica"
            assert any(e["name"] == "failover" for e in b_events)
            failed_over_ids = set(died[0]["args"]["trace_ids"])
            router_pid = died[0]["pid"]
            assert any(
                e["pid"] != router_pid
                and failed_over_ids & set(e["args"].get("trace_ids") or [])
                for e in b_events
            ), "the failed-over trace id never resolved on a replica track"
            # >= 3 processes merged: the router + the two survivors (the
            # victim's stream may carry no completed span trees)
            pids = {e["pid"] for e in b_events}
            assert len(pids) >= 3, pids
        finally:
            if poller is not None:
                poller.stop()
            procs = [p for p, _ in replicas] + (
                [relaunched] if relaunched else []
            ) + ([fleet_proc] if fleet_proc else [])
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    try:
                        proc.communicate(timeout=30)
                    except subprocess.TimeoutExpired:
                        pass


class TestRollingRestartAcceptanceDrill:
    @pytest.mark.slow
    def test_rolling_restart_under_load_with_shared_cache(self, tmp_path):
        """The second ISSUE 13 acceptance bar: `nm03-fleet restart`
        across three replicas sharing one --compile-cache-dir completes
        with fleet capacity never below ⅔, every warm /readyz reporting
        builds==0 (cache hits), and a concurrent loadgen run finishing
        with zero errors."""
        from nm03_capstone_project_tpu.fleet.manager import rolling_restart
        from nm03_capstone_project_tpu.serving import loadgen

        env = _cpu_env()
        cache_dir = tmp_path / "cache"
        cache_dir.mkdir()
        ports = _free_ports(4)
        replicas = [
            _spawn_replica(
                port, tmp_path, i,
                ["--compile-cache-dir", str(cache_dir),
                 "--request-timeout-s", "300"],
                env,
            )
            for i, port in enumerate(ports[:3])
        ]
        fleet_proc = None
        spawned = []
        try:
            _wait_replicas_ready(replicas)
            targets = [f"127.0.0.1:{p}" for p in ports[:3]]
            fleet_proc = subprocess.Popen(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.fleet.cli", "serve",
                    "--replicas", ",".join(targets),
                    "--port", str(ports[3]),
                    "--health-interval-s", "0.25",
                    "--probe-interval-s", "0.4",
                    "--proxy-timeout-s", "240",
                ],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env, cwd=REPO,
            )
            fleet_url = f"http://127.0.0.1:{ports[3]}"
            assert _wait_http(f"{fleet_url}/readyz", 60)

            results_json = tmp_path / "loadgen.json"
            lg_rc = []

            def run_loadgen():
                lg_rc.append(loadgen.main([
                    "--targets", fleet_url,
                    "--requests", "60", "--rate", "3",
                    "--timeout-s", "240", "--warmup", "2",
                    "--height", str(CANVAS), "--width", str(CANVAS),
                    "--results-json", str(results_json),
                ]))

            lg = threading.Thread(target=run_loadgen, daemon=True)
            lg.start()
            time.sleep(0.5)  # a little traffic before the first drain

            def spawn(argv, **kw):
                proc = subprocess.Popen(
                    argv, cwd=kw.get("cwd"), env=env,
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    start_new_session=True,
                )
                spawned.append(proc)
                return proc

            report = rolling_restart(
                targets,
                compile_cache_dir=str(cache_dir),
                drain_timeout_s=120, warm_timeout_s=300, poll_s=0.1,
                fleet_url=fleet_url, spawn=spawn, emit=lambda m: None,
            )
            assert report["ok"] is True
            assert len(report["replicas"]) == 3
            for entry in report["replicas"]:
                assert entry["ok"], entry
                assert entry["new_pid"] != entry["old_pid"]
                assert entry["new_id"] != entry["old_id"]
                # the PR-9 payoff: the warm restart NEVER compiled
                assert entry["builds"] == 0, entry
                assert entry["cache_hits"] >= 1, entry
            lg.join(timeout=400)
            assert lg_rc == [0]
            summary = json.loads(results_json.read_text())
            # zero errors through three consecutive replica restarts
            bad = {
                k: v for k, v in summary["statuses"].items() if k != "ok"
            }
            assert not bad, summary["statuses"]
            assert summary["requests_ok"] == 60
            # capacity never dropped below the (N-1)/N floor
            assert summary["fleet_capacity_min_observed"] is not None
            assert summary["fleet_capacity_min_observed"] >= 2 / 3 - 1e-6, (
                summary["fleet_capacity_min_observed"]
            )
            status, st = _readyz(fleet_url)
            assert status == 200 and st["replicas"]["ready"] == 3
        finally:
            if fleet_proc is not None and fleet_proc.poll() is None:
                fleet_proc.send_signal(signal.SIGTERM)
                try:
                    fleet_proc.communicate(timeout=60)
                except subprocess.TimeoutExpired:
                    fleet_proc.kill()
            for proc, _ in replicas:
                if proc.poll() is None:
                    proc.kill()
                    try:
                        proc.communicate(timeout=30)
                    except subprocess.TimeoutExpired:
                        pass
            for proc in spawned:
                if proc.poll() is None:
                    proc.kill()
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pass
