import numpy as np
import pytest
import scipy.ndimage as ndi

from nm03_capstone_project_tpu.ops import (
    extend_edges,
    gaussian_blur,
    sharpen,
    vector_median_filter,
    vector_median_filter_multichannel,
    vector_median_filter_sort,
)


def test_median_matches_scipy_interior(rng):
    x = rng.random((40, 40)).astype(np.float32)
    out = np.asarray(vector_median_filter(x, 7))
    expected = ndi.median_filter(x, size=7, mode="nearest")
    np.testing.assert_allclose(out, expected, atol=1e-6)


def test_median_size3(rng):
    x = rng.random((16, 16)).astype(np.float32)
    out = np.asarray(vector_median_filter(x, 3))
    expected = ndi.median_filter(x, size=3, mode="nearest")
    np.testing.assert_allclose(out, expected, atol=1e-6)


def test_median_batched(rng):
    x = rng.random((3, 20, 20)).astype(np.float32)
    out = np.asarray(vector_median_filter(x, 5))
    for i in range(3):
        np.testing.assert_allclose(
            out[i], ndi.median_filter(x[i], size=5, mode="nearest"), atol=1e-6
        )


class TestNetworkMedian:
    """The column-presorted Batcher network path vs the sort oracle.

    Bit-identical equality (not allclose): both paths only MOVE input values
    — no arithmetic — so any deviation is an algorithmic bug, not float
    noise.
    """

    @pytest.mark.slow
    def test_bit_identical_to_sort_oracle(self, rng):
        for size in (3, 5, 7, 9):
            for shape in ((33, 47), (8, 8), (7, 7)):
                x = rng.random(shape).astype(np.float32)
                got = np.asarray(vector_median_filter(x, size))
                want = np.asarray(vector_median_filter_sort(x, size))
                np.testing.assert_array_equal(got, want, err_msg=f"{size} {shape}")

    def test_heavy_ties(self, rng):
        # quantized values force many equal samples through the network
        for size in (3, 5, 7):
            x = rng.integers(0, 4, (40, 40)).astype(np.float32)
            np.testing.assert_array_equal(
                np.asarray(vector_median_filter(x, size)),
                np.asarray(vector_median_filter_sort(x, size)),
            )

    def test_batched_and_size1(self, rng):
        x = rng.random((3, 24, 24)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(vector_median_filter(x, 7)),
            np.asarray(vector_median_filter_sort(x, 7)),
        )
        np.testing.assert_array_equal(np.asarray(vector_median_filter(x, 1)), x)

    def test_batcher_networks_sort_correctly(self, rng):
        # 0-1 principle: a comparator network sorts all inputs iff it sorts
        # all 0-1 inputs; exhaustive for the small vertical-sort widths.
        # Vectorized: lane c of every value array holds 0-1 case c, so one
        # network pass checks all 2^n cases at once.
        from nm03_capstone_project_tpu.ops.median import (
            _apply_pairs,
            _oddeven_sort_pairs,
        )

        for n in (2, 4, 8, 16):
            pairs = []
            _oddeven_sort_pairs(0, n, pairs)
            cases = ((np.arange(2**n)[None, :] >> np.arange(n)[:, None]) & 1)
            vals = [cases[i].astype(np.float32) for i in range(n)]
            _apply_pairs(vals, pairs)
            out = np.stack(vals)
            want = np.sort(cases.astype(np.float32), axis=0)
            np.testing.assert_array_equal(out, want, err_msg=f"sort n={n}")

    def test_batcher_merge_networks_exhaustive(self):
        # every merge width the median's run-merge trees can emit — 4/8 for
        # the small kernels (k=3: p_run=4, total=16), up to 64 for k=7/9 —
        # over all (n/2+1)^2 sorted-0-1-half combinations: the exhaustive
        # 0-1 check specialised to merging, one vectorized pass per width
        from nm03_capstone_project_tpu.ops.median import (
            _apply_pairs,
            _oddeven_merge_pairs,
        )

        for total in (4, 8, 16, 32, 64):
            half = total // 2
            pairs = []
            _oddeven_merge_pairs(0, total, 1, pairs)
            # case (i, j) = sorted half with i ones || sorted half with j ones
            ones_a = np.arange(half + 1)[:, None]
            ones_b = np.arange(half + 1)[None, :]
            shape2d = (half + 1, half + 1)
            cases = []
            for pos in range(total):
                if pos < half:
                    lane = np.broadcast_to(pos >= (half - ones_a), shape2d)
                else:
                    lane = np.broadcast_to((pos - half) >= (half - ones_b), shape2d)
                cases.append(lane.astype(np.float32).ravel())
            vals = list(cases)
            _apply_pairs(vals, pairs)
            out = np.stack(vals)
            want = np.sort(np.stack(cases), axis=0)
            np.testing.assert_array_equal(out, want, err_msg=f"merge n={total}")


class TestPrunedSelectionNetwork:
    """The pruned selection network (ops.selection_network) vs the odd-even
    merge baseline: fewer ops, identical values (ISSUE 2 tentpole)."""

    def test_comparator_counts_measurably_fewer(self):
        # the acceptance criterion: the pruned network uses measurably
        # fewer compare-exchange ops than the odd-even merge baseline,
        # with the count asserted — and the shared (Pallas) variant fewer
        # still. Exact values pinned so a planner regression is loud.
        from nm03_capstone_project_tpu.ops.selection_network import (
            comparator_counts,
        )

        for k, full, pruned, shared in (
            (3, 38, 16, 16),
            (5, 226, 110, 72),
            (7, 566, 346, 262),
            (9, 1374, 722, 352),
        ):
            cc = comparator_counts(k)
            assert cc["merge_minmax_full"] == full, k
            assert cc["merge_minmax_pruned"] <= pruned, k
            assert cc["merge_minmax_pruned_shared"] <= shared, k
            # "measurably fewer": at least 1.5x at every window size
            assert cc["merge_minmax_full"] >= 1.5 * cc["merge_minmax_pruned"], k
            assert (
                cc["merge_minmax_pruned_shared"] <= cc["merge_minmax_pruned"]
            ), k

    def test_pruned_bit_identical_to_merge_baseline(self, rng):
        from nm03_capstone_project_tpu.ops.median import (
            vector_median_filter_merge,
        )

        for size in (3, 5, 7, 9):
            for shape in ((33, 47), (8, 8), (7, 7)):
                x = rng.random(shape).astype(np.float32)
                np.testing.assert_array_equal(
                    np.asarray(vector_median_filter(x, size)),
                    np.asarray(vector_median_filter_merge(x, size)),
                    err_msg=f"{size} {shape}",
                )

    def test_shared_plan_equals_unshared(self, rng):
        # the Pallas variant (cross-window shared subtree merges) must
        # compute the same values through the shift/domain machinery
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.ops.median import (
            _execute_plan,
            _presorted_rows,
        )
        from nm03_capstone_project_tpu.ops.selection_network import (
            median_merge_plan,
        )

        for k in (3, 5, 7):
            r = k // 2
            x = rng.random((19, 23)).astype(np.float32)
            rows = _presorted_rows(jnp.asarray(x), k)
            padded = [
                jnp.pad(a, [(0, 0), (r, r)], mode="edge") for a in rows
            ]
            a = _execute_plan(median_merge_plan(k, share=False), padded, 23)
            b = _execute_plan(median_merge_plan(k, share=True), padded, 23)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rank_select_identity_brute_force(self):
        # rank_p(A ∪ B) == max_{i+j=p} min(A_i, B_j) with +inf past the
        # ends — the identity the planner's final stage rests on, checked
        # against sorted(A+B) for every rank, duplicates included
        import random

        from nm03_capstone_project_tpu.ops.selection_network import (
            _Builder,
            _rank_select,
        )

        random.seed(11)
        for _ in range(300):
            la, lb = random.randint(1, 6), random.randint(1, 6)
            av = sorted(random.randint(0, 4) for _ in range(la))
            bv = sorted(random.randint(0, 4) for _ in range(lb))
            union = sorted(av + bv)
            for rho in range(la + lb):
                bld = _Builder(la + lb)
                out = _rank_select(
                    bld,
                    [(i, 0) for i in range(la)],
                    [(la + i, 0) for i in range(lb)],
                    rho,
                )
                vals = dict(enumerate(av + bv))
                for i, (kind, (a, _), (b, _)) in sorted(bld.nodes.items()):
                    vals[i] = (
                        min(vals[a], vals[b])
                        if kind == "min"
                        else max(vals[a], vals[b])
                    )
                assert vals[out[0]] == union[rho], (av, bv, rho)

    def test_unshared_plans_correct_on_random_columns(self):
        # plan-level check independent of jax: in the UNSHARED plans every
        # derived ref is shift-0 (asserted — the property that lets the XLA
        # executor stay one fused elementwise DAG), so the op list can be
        # executed on plain ints per window; checked against sorted() for
        # random tied columns. (The shared plan's shifted refs need the
        # array executor — covered by test_shared_plan_equals_unshared.)
        import random

        from nm03_capstone_project_tpu.ops.selection_network import (
            median_merge_plan,
        )

        random.seed(5)
        for k in (3, 5, 7):
            for prune in (True, False):
                plan = median_merge_plan(k, prune=prune, share=False)
                assert all(
                    (a < k or ash == 0) and (b < k or bsh == 0)
                    for _, _, a, ash, b, bsh in plan.ops
                ), "unshared plan must not shift derived values"
                for _ in range(200):
                    cols = [
                        sorted(random.randint(0, 6) for _ in range(k))
                        for _ in range(k)
                    ]
                    want = sorted(v for c in cols for v in c)[(k * k) // 2]
                    vals = {}

                    def read(vid, s, cols=cols, vals=vals, k=k):
                        if vid < k:
                            return cols[s + k // 2][vid]  # column at shift s
                        return vals[vid]

                    for kind, out, a, ash, b, bsh in plan.ops:
                        av, bv = read(a, ash), read(b, bsh)
                        vals[out] = min(av, bv) if kind == "min" else max(av, bv)
                    assert read(*plan.out) == want, (k, prune, cols)


def test_vector_median_scalar_channel_agrees(rng):
    """For C=1 the true L1 vector median equals the scalar median."""
    x = rng.random((18, 18)).astype(np.float32)
    vm = np.asarray(vector_median_filter_multichannel(x[None], 5))[0]
    sm = np.asarray(vector_median_filter(x, 5))
    np.testing.assert_allclose(vm, sm, atol=1e-6)


def test_vector_median_multichannel_picks_window_sample(rng):
    x = rng.random((3, 12, 12)).astype(np.float32)
    vm = np.asarray(vector_median_filter_multichannel(x, 3))
    # every output vector must be one of the window's input vectors
    xpad = np.pad(x, [(0, 0), (1, 1), (1, 1)], mode="edge")
    for r in range(12):
        for c in range(3, 5):
            window = xpad[:, r : r + 3, c : c + 3].reshape(3, -1).T
            assert any(np.allclose(vm[:, r, c], w, atol=1e-6) for w in window)


def test_gaussian_blur_matches_scipy(rng):
    x = rng.random((32, 32)).astype(np.float32)
    out = np.asarray(gaussian_blur(x, sigma=1.0, size=9))
    expected = ndi.gaussian_filter(x, sigma=1.0, mode="nearest", radius=4)
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_sharpen_identity_on_constant():
    x = np.full((16, 16), 3.25, np.float32)
    out = np.asarray(sharpen(x))
    np.testing.assert_allclose(out, x, atol=1e-6)


def test_sharpen_amplifies_edge(rng):
    x = np.zeros((16, 16), np.float32)
    x[:, 8:] = 1.0
    out = np.asarray(sharpen(x, gain=2.0, sigma=0.5, size=9))
    # unsharp masking overshoots on both sides of the edge
    assert out[:, 7].max() < 0.0
    assert out[:, 8].min() > 1.0


def test_extend_edges_replicates_true_boundary():
    x = np.zeros((6, 6), np.float32)
    x[:4, :5] = np.arange(20, dtype=np.float32).reshape(4, 5)
    dims = np.array([4, 5], dtype=np.int32)
    out = np.asarray(extend_edges(x, dims))
    np.testing.assert_array_equal(out[:4, :5], x[:4, :5])
    assert (out[4:, :5] == x[3, [0, 1, 2, 3, 4]]).all()
    assert (out[:4, 5:] == x[:4, 4:5]).all()
    assert (out[4:, 5:] == x[3, 4]).all()
