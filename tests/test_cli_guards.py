"""Negative-path guards in the CLI plumbing (cli.common)."""

import argparse

import pytest

from nm03_capstone_project_tpu.cli import common
from nm03_capstone_project_tpu.config import PipelineConfig


def _ns(**kw):
    return argparse.Namespace(**kw)


class TestInitDistributed:
    def test_no_flag_is_single_process(self):
        assert common.init_distributed(_ns(distributed=False)) == (0, 1)

    def test_explicit_nproc_that_joins_nothing_is_fatal(self, monkeypatch):
        # every worker silently processing the whole cohort into the same
        # tree is the worst launcher failure mode — it must be a hard error
        from nm03_capstone_project_tpu.parallel import distributed

        monkeypatch.setattr(distributed, "initialize", lambda **kw: False)
        monkeypatch.setattr(
            distributed,
            "process_info",
            lambda: {"process_index": 0, "process_count": 1},
        )
        with pytest.raises(RuntimeError, match="joined no cluster"):
            common.init_distributed(
                _ns(
                    distributed=True,
                    coordinator_address="127.0.0.1:1",
                    num_processes=2,
                    process_id=0,
                )
            )

    def test_autodetect_miss_degrades_with_warning(self, monkeypatch, capsys):
        from nm03_capstone_project_tpu.parallel import distributed

        monkeypatch.setattr(distributed, "initialize", lambda **kw: False)
        monkeypatch.setattr(
            distributed,
            "process_info",
            lambda: {"process_index": 0, "process_count": 1},
        )
        rank, world = common.init_distributed(
            _ns(
                distributed=True,
                coordinator_address=None,
                num_processes=None,
                process_id=None,
            )
        )
        assert (rank, world) == (0, 1)
        assert "no cluster detected" in capsys.readouterr().err


class TestModelCheckpointGuards:
    def _ckpt(self, tmp_path, meta):
        import jax

        from nm03_capstone_project_tpu.models import init_unet
        from nm03_capstone_project_tpu.models.checkpoint import save_params

        path = tmp_path / "ckpt"
        save_params(path, init_unet(jax.random.PRNGKey(0), base=8), meta=meta)
        return path

    @pytest.mark.slow
    def test_norm_clip_mismatch_is_fatal(self, tmp_path):
        path = self._ckpt(
            tmp_path,
            {
                "canvas": 256,
                "model_3d": False,
                "norm": [0.5, 2.5, 0.0, 10000.0],
                "clip": [0.68, 4000.0],
            },
        )
        cfg = PipelineConfig(clip_high=2000.0)  # deployment flag conflicts
        with pytest.raises(SystemExit, match="clip constants"):
            common.load_model_checkpoint(_ns(model=str(path)), cfg)

    @pytest.mark.slow
    def test_matching_meta_loads(self, tmp_path):
        path = self._ckpt(
            tmp_path,
            {
                "canvas": 256,
                "model_3d": False,
                "norm": [0.5, 2.5, 0.0, 10000.0],
                "clip": [0.68, 4000.0],
            },
        )
        params = common.load_model_checkpoint(_ns(model=str(path)), PipelineConfig())
        assert params is not None

    @pytest.mark.slow
    def test_metaless_checkpoint_loads_permissively(self, tmp_path):
        # older checkpoints without meta: no constants to check against
        path = self._ckpt(tmp_path, None)
        params = common.load_model_checkpoint(_ns(model=str(path)), PipelineConfig())
        assert params is not None
