import jax
import numpy as np

from nm03_capstone_project_tpu.ops import seed_mask


def reference_seed_points(width: int, height: int):
    """Literal transcription of the reference's seed loop semantics.

    (src/test/test_pipeline.cpp:79-106: center seed, 4 offset seeds, then a
    grid x in [w/4, 3w/4) step w/10, y in [h/4, 3h/4) step h/10 — all C++
    integer division.)
    """
    cx, cy = width // 2, height // 2
    ox, oy = width // 8, height // 8
    pts = {(cx, cy), (cx + ox, cy), (cx - ox, cy), (cx, cy + oy), (cx, cy - oy)}
    x = width // 4
    while x < width * 3 // 4:
        y = height // 4
        while y < height * 3 // 4:
            pts.add((x, y))
            y += max(height // 10, 1)
        x += max(width // 10, 1)
    # clip to image bounds (a seed outside the image can never grow)
    return {(x, y) for (x, y) in pts if 0 <= x < width and 0 <= y < height}


def mask_to_points(mask: np.ndarray):
    ys, xs = np.nonzero(mask)
    return set(zip(xs.tolist(), ys.tolist()))


def test_seed_mask_matches_reference_loops():
    for h, w in [(256, 256), (240, 256), (100, 100), (256, 230), (101, 255)]:
        dims = np.array([h, w], dtype=np.int32)
        m = np.asarray(seed_mask(dims, (256, 256)))
        assert mask_to_points(m) == reference_seed_points(w, h), (h, w)


def test_seed_mask_batched_and_jitted():
    dims = np.array([[256, 256], [128, 200]], dtype=np.int32)
    f = jax.jit(lambda d: seed_mask(d, (256, 256)))
    m = np.asarray(f(dims))
    assert m.shape == (2, 256, 256)
    for i, (h, w) in enumerate(dims.tolist()):
        assert mask_to_points(m[i]) == reference_seed_points(w, h)


def test_seed_mask_no_seeds_in_padding():
    dims = np.array([64, 64], dtype=np.int32)
    m = np.asarray(seed_mask(dims, (256, 256)))
    assert not m[64:, :].any()
    assert not m[:, 64:].any()
