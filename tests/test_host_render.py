"""Host renderer vs device renderer agreement, and the dual-mode drivers.

The batch drivers default to host-side export rendering (render.host_render)
so only the mask crosses the host<->device link; these tests pin that the
host path reproduces the canonical device renderer (render.render) — exactly
for the nearest-sampled segmentation render, and to within one 8-bit count
for the bilinear grayscale render (XLA may contract the lerp into FMAs) —
and that both driver modes produce complete, mutually consistent exports.
"""

import numpy as np
import pytest

from nm03_capstone_project_tpu.cli.runner import CohortProcessor
from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_slice, write_synthetic_cohort
from nm03_capstone_project_tpu.render.host_render import (
    host_render_gray,
    host_render_pair,
    host_render_segmentation,
)
from nm03_capstone_project_tpu.render.render import (
    render_gray,
    render_pair,
    render_segmentation,
)

CFG = PipelineConfig(canvas=128, render_size=128)


def _slice_on_canvas(h, w, canvas=128, seed=3):
    px = phantom_slice(h, w, seed=seed, lesion_radius=0.18)
    padded = np.zeros((canvas, canvas), np.float32)
    padded[:h, :w] = px
    dims = np.asarray([h, w], np.int32)
    mask = np.zeros((canvas, canvas), np.uint8)
    mask[h // 3 : h // 2, w // 3 : w // 2] = 1
    return padded, mask, dims


@pytest.mark.parametrize("hw", [(128, 128), (100, 73), (64, 128), (101, 101)])
def test_host_matches_device_segmentation_exactly(hw):
    padded, mask, dims = _slice_on_canvas(*hw)
    dev = np.asarray(render_segmentation(mask, dims, 128, 0.6, 1.0, 2))
    host = host_render_segmentation(mask, dims, 128, 0.6, 1.0, 2)
    np.testing.assert_array_equal(dev, host)


@pytest.mark.parametrize("hw", [(128, 128), (100, 73), (64, 128), (101, 101)])
def test_host_matches_device_gray_within_one_count(hw):
    padded, _, dims = _slice_on_canvas(*hw)
    dev = np.asarray(render_gray(padded, dims, 128)).astype(np.int16)
    host = host_render_gray(padded, dims, 128).astype(np.int16)
    diff = np.abs(dev - host)
    assert diff.max() <= 1
    # rounding disagreements are isolated interpolated pixels, not drift
    assert (diff > 0).mean() < 0.01


def test_host_pair_matches_device_pair():
    padded, mask, dims = _slice_on_canvas(100, 73)
    dg, ds = (np.asarray(a) for a in render_pair(padded, mask, dims, CFG))
    hg, hs = host_render_pair(padded, mask, dims, CFG)
    np.testing.assert_array_equal(ds, hs)
    assert np.abs(dg.astype(np.int16) - hg.astype(np.int16)).max() <= 1


def test_render_stage_validated():
    with pytest.raises(ValueError, match="render_stage"):
        BatchConfig(render_stage="gpu")


class TestDriverModes:
    @pytest.fixture(scope="class")
    def cohort(self, tmp_path_factory):
        root = tmp_path_factory.mktemp("hr_cohort")
        write_synthetic_cohort(root, n_patients=1, n_slices=4, height=128, width=120)
        return root

    def test_both_render_stages_export_full_cohort(self, cohort, tmp_path):
        results = {}
        for stage in ("host", "device"):
            out = tmp_path / stage
            proc = CohortProcessor(
                cohort,
                out,
                cfg=CFG,
                batch_cfg=BatchConfig(batch_size=3, io_workers=2, render_stage=stage),
                mode="parallel",
            )
            summary = proc.process_all_patients()
            assert summary.succeeded_slices == 4, stage
            jpgs = sorted(p.name for p in out.rglob("*.jpg"))
            assert len(jpgs) == 8, stage
            results[stage] = jpgs
        assert results["host"] == results["device"]  # same file set

    def test_sequential_equals_parallel_on_host_path(self, cohort, tmp_path):
        import hashlib

        def digest(root):
            h = hashlib.sha256()
            for p in sorted(root.rglob("*.jpg")):
                h.update(p.name.encode())
                h.update(p.read_bytes())
            return h.hexdigest()

        outs = {}
        for mode in ("sequential", "parallel"):
            out = tmp_path / mode
            proc = CohortProcessor(
                cohort,
                out,
                cfg=CFG,
                batch_cfg=BatchConfig(
                    batch_size=3, io_workers=2, render_stage="host"
                ),
                mode=mode,
            )
            proc.process_all_patients()
            outs[mode] = digest(out)
        assert outs["sequential"] == outs["parallel"]


class TestNativeRenderPair:
    """csrc nm03_render_pair must be BYTE-identical to the NumPy host
    renderer — it is the same math, mirrored operation for operation (the
    library builds with -ffp-contract=off so the compiler cannot fuse the
    lerp into FMAs NumPy does not use)."""

    def test_byte_identical_random_shapes(self):
        native = pytest.importorskip(
            "nm03_capstone_project_tpu.native", reason="native layer"
        )
        if not native.available():
            pytest.skip("native library not buildable here")
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.render.host_render import host_render_pair

        cfg = PipelineConfig()
        rng = np.random.default_rng(7)
        for _ in range(10):
            h = int(rng.integers(90, 250))
            w = int(rng.integers(90, 250))
            px = np.zeros((256, 256), np.float32)
            px[:h, :w] = rng.random((h, w), np.float32) * 4000
            mask = np.zeros((256, 256), np.uint8)
            mask[:h, :w] = (rng.random((h, w)) > 0.8).astype(np.uint8)
            dims = np.asarray([h, w], np.int32)
            g_np, s_np = host_render_pair(px, mask, dims, cfg)
            g_nat, s_nat = native.render_pair_native(px, mask, dims, cfg)
            np.testing.assert_array_equal(g_nat, g_np)
            np.testing.assert_array_equal(s_nat, s_np)

    def test_blank_and_full_masks(self):
        native = pytest.importorskip(
            "nm03_capstone_project_tpu.native", reason="native layer"
        )
        if not native.available():
            pytest.skip("native library not buildable here")
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.render.host_render import host_render_pair

        cfg = PipelineConfig()
        px = np.zeros((128, 128), np.float32)
        px[:100, :100] = 7.0  # constant region: windowing guard path
        dims = np.asarray([100, 100], np.int32)
        for mask_val in (0, 1):
            mask = np.full((128, 128), mask_val, np.uint8)
            g_np, s_np = host_render_pair(px, mask, dims, cfg)
            g_nat, s_nat = native.render_pair_native(px, mask, dims, cfg)
            np.testing.assert_array_equal(g_nat, g_np)
            np.testing.assert_array_equal(s_nat, s_np)

    def test_bad_dims_rejected(self):
        native = pytest.importorskip(
            "nm03_capstone_project_tpu.native", reason="native layer"
        )
        if not native.available():
            pytest.skip("native library not buildable here")
        from nm03_capstone_project_tpu.config import PipelineConfig

        cfg = PipelineConfig()
        px = np.zeros((64, 64), np.float32)
        mask = np.zeros((64, 64), np.uint8)
        with pytest.raises(ValueError, match="render"):
            native.render_pair_native(px, mask, np.asarray([128, 64]), cfg)
