"""Per-lane fault domains (ISSUE 8): quarantine, probation, re-dispatch.

Four layers, mirroring tests/test_serving_lanes.py's structure:

* the :class:`LaneFaultDomains` state machine alone (jax-free): every
  transition, its idempotence, and its gauge/counter/event telemetry;
* the batcher's re-dispatch path against lane-aware fakes: a chunk whose
  lane quarantines mid-dispatch rides a ``requeue`` hop to a healthy lane
  (riders never fail), fan-out targets exclude quarantined lanes, the
  coalescing window shrinks with the healthy set, and the requeue budget
  bounds the loop;
* the real ``WarmExecutor`` under a lane-targeted fault plan: a wedged
  dispatch quarantines ONE lane (with a flight-recorder auto-dump), the
  probation probe reinstates it off the request path, and only an
  every-lane wedge trips the process-wide CPU fallback;
* the chaos acceptance drill, in a real ``nm03-serve`` subprocess: four
  lanes, a deterministic lane-2 wedge under 16-way concurrent load,
  continuous 200s with bit-identical masks, ``/readyz`` 200 at reduced
  capacity, quarantine + reinstatement visible in the labeled lane
  metrics, and the CPU fallback NOT tripped.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.lanes import (
    HEALTHY,
    PROBATION,
    QUARANTINED,
    LaneFaultDomains,
    LaneQuarantined,
)
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue, ServeRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


class _Events:
    def __init__(self):
        self.records = []

    def emit(self, event, level="INFO", **fields):
        rec = {"event": event, "level": level, **fields}
        self.records.append(rec)
        return rec

    def of(self, event):
        return [r for r in self.records if r["event"] == event]


class _Obs:
    """Registry + event recorder stub (the slice of RunContext lanes.py uses)."""

    def __init__(self):
        from nm03_capstone_project_tpu.obs.metrics import MetricsRegistry

        self.registry = MetricsRegistry()
        self.events = _Events()


def _reqs(n, hw=16):
    return [
        ServeRequest(
            request_id=f"r{i}",
            pixels=np.ones((hw, hw), np.float32),
            dims=(hw, hw),
        )
        for i in range(n)
    ]


# -- the state machine alone ------------------------------------------------


class TestLaneFaultDomains:
    def test_initial_state_all_healthy_with_gauges(self):
        obs = _Obs()
        fleet = LaneFaultDomains(4, obs=obs)
        assert len(fleet) == 4
        assert fleet.healthy_lanes() == [0, 1, 2, 3]
        assert fleet.healthy_count() == 4 and fleet.quarantined_count() == 0
        # series exist at 0 from construction: "healthy" is distinguishable
        # from "never reported" (the labeled --expect-gauge contract)
        for lane in range(4):
            g = obs.registry.get("serving_lane_state", lane=str(lane))
            assert g is not None and g.value == 0

    def test_bad_sizes_rejected(self):
        with pytest.raises(ValueError, match="n_lanes"):
            LaneFaultDomains(0)
        fleet = LaneFaultDomains(2)
        with pytest.raises(ValueError, match="lane"):
            fleet.quarantine(2, "deadline")

    def test_quarantine_transition_and_telemetry(self):
        obs = _Obs()
        fleet = LaneFaultDomains(3, obs=obs)
        changed, left = fleet.quarantine(1, "deadline", trace_ids=["t-1", "t-2"])
        assert changed and left == 2
        assert fleet.state(1) == QUARANTINED and fleet.cause(1) == "deadline"
        assert fleet.healthy_lanes() == [0, 2]
        assert fleet.quarantined_count() == 1
        assert obs.registry.get("serving_lane_state", lane="1").value == 2
        assert (
            obs.registry.get(
                "serving_lane_quarantines_total", lane="1", cause="deadline"
            ).value
            == 1
        )
        (ev,) = obs.events.of("lane_quarantined")
        assert ev["level"] == "WARNING" and ev["lane"] == 1
        assert ev["healthy_remaining"] == 2
        assert ev["trace_ids"] == ["t-1", "t-2"]

    def test_quarantine_idempotent(self):
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        assert fleet.quarantine(0, "deadline") == (True, 1)
        # a racing second dispatch on the same sick lane: no double count
        assert fleet.quarantine(0, "device_lost") == (False, 1)
        assert fleet.cause(0) == "deadline"  # first cause wins
        assert (
            obs.registry.get(
                "serving_lane_quarantines_total", lane="0", cause="deadline"
            ).value
            == 1
        )
        assert (
            obs.registry.get(
                "serving_lane_quarantines_total", lane="0", cause="device_lost"
            )
            is None
        )
        assert len(obs.events.of("lane_quarantined")) == 1

    def test_last_lane_quarantine_reports_zero_healthy(self):
        fleet = LaneFaultDomains(2)
        fleet.quarantine(0, "deadline")
        changed, left = fleet.quarantine(1, "device_lost")
        assert changed and left == 0
        assert fleet.healthy_lanes() == []

    def test_probation_claim_is_exclusive(self):
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        assert not fleet.begin_probation(0)  # healthy: nothing to probe
        fleet.quarantine(0, "deadline")
        assert fleet.begin_probation(0)
        assert fleet.state(0) == PROBATION
        assert not fleet.begin_probation(0)  # second prober bounces
        # probation still takes no traffic
        assert fleet.healthy_lanes() == [1]
        assert fleet.quarantined_count() == 1
        assert obs.registry.get("serving_lane_state", lane="0").value == 1

    def test_reinstate_only_from_probation(self):
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        assert not fleet.reinstate(0)  # healthy: no-op
        fleet.quarantine(0, "deadline")
        assert not fleet.reinstate(0)  # must go through probation
        fleet.begin_probation(0)
        assert fleet.reinstate(0)
        assert fleet.state(0) == HEALTHY and fleet.cause(0) is None
        assert fleet.healthy_lanes() == [0, 1]
        assert obs.registry.get("serving_lane_state", lane="0").value == 0
        assert (
            obs.registry.get("serving_lane_reinstated_total", lane="0").value
            == 1
        )
        assert len(obs.events.of("lane_reinstated")) == 1

    def test_failed_probation_recounts_quarantine(self):
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        fleet.quarantine(1, "deadline")
        fleet.begin_probation(1)
        assert fleet.fail_probation(1)
        assert fleet.state(1) == QUARANTINED
        assert fleet.cause(1) == "probe_failed"
        assert (
            obs.registry.get(
                "serving_lane_quarantines_total", lane="1", cause="probe_failed"
            ).value
            == 1
        )
        assert not fleet.fail_probation(1)  # not in probation anymore
        snap = fleet.snapshot()
        assert snap[1]["quarantines"] == 2  # deadline + probe_failed

    def test_obs_none_is_fine(self):
        fleet = LaneFaultDomains(2, obs=None)
        fleet.quarantine(0, "deadline")
        fleet.begin_probation(0)
        fleet.reinstate(0)
        assert fleet.healthy_count() == 2

    def test_last_lane_quarantine_retires_the_fleet(self):
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        assert not fleet.retired
        fleet.quarantine(0, "deadline")
        fleet.begin_probation(0)  # a canary is in flight...
        # ...when the LAST healthy lane drains: retired flips in the same
        # critical section as the quarantine
        changed, left = fleet.quarantine(1, "device_lost")
        assert changed and left == 0 and fleet.retired
        # the passing canary is refused — a lane must not resurrect into
        # a replica whose one-way CPU degradation already tripped (the
        # check-then-act window the retire flag closes)
        assert not fleet.reinstate(0)
        assert fleet.state(0) == PROBATION
        assert fleet.healthy_count() == 0
        assert obs.registry.get("serving_lane_state", lane="0").value == 1
        assert not obs.events.of("lane_reinstated")

    def test_fail_probation_counts_but_never_dumps(self, flight_dir):
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        fleet.quarantine(1, "deadline")
        dumps = glob.glob(str(flight_dir / "nm03_flight_*"))
        assert len(dumps) == 1  # the original wedge's post-mortem
        fleet.begin_probation(1)
        assert fleet.fail_probation(1)
        # counted as a fresh quarantine with the shared event shape...
        ev = obs.events.of("lane_quarantined")[-1]
        assert ev["cause"] == "probe_failed"
        assert ev["healthy_remaining"] == 1
        # ...but deliberately NOT dumped: a sick chip fails a canary every
        # probe interval, and each dump would bury the wedge's evidence
        assert glob.glob(str(flight_dir / "nm03_flight_*")) == dumps

    def test_stale_dispatch_cannot_steal_a_probation_claim(self, flight_dir):
        # dispatch timeouts outlive the probe interval: a chunk already in
        # flight when its lane quarantined reports the SAME wedge after
        # the prober claimed the lane — it must not double-count the
        # incident, write a second dump, or knock the canary's claim back
        # to QUARANTINED (which would no-op its reinstate and idle the
        # lane one extra probe round)
        obs = _Obs()
        fleet = LaneFaultDomains(2, obs=obs)
        fleet.quarantine(1, "deadline", trace_ids=["t-a"])
        dumps = glob.glob(str(flight_dir / "nm03_flight_*"))
        fleet.begin_probation(1)
        changed, left = fleet.quarantine(1, "deadline", trace_ids=["t-b"])
        assert not changed and left == 1
        assert fleet.state(1) == PROBATION  # the claim survives
        assert (
            obs.registry.get(
                "serving_lane_quarantines_total", lane="1", cause="deadline"
            ).value
            == 1
        )
        assert glob.glob(str(flight_dir / "nm03_flight_*")) == dumps
        assert fleet.reinstate(1)  # the canary's pass still lands


# -- the batcher's re-dispatch path (lane-aware fakes, no jax) --------------


class QuarantiningExecutor:
    """Lane-aware fake: lanes in ``sick`` raise LaneQuarantined and leave
    the healthy set, mimicking the real executor's quarantine outcome."""

    supports_trace = False

    def __init__(self, buckets=(1, 2, 4), lanes=4, sick=(), canvas=16, min_dim=4):
        self.cfg = SimpleNamespace(canvas=canvas, min_dim=min_dim)
        self.buckets = tuple(buckets)
        self.lane_count = lanes
        self.calls = []
        self._healthy = [ln for ln in range(lanes) if ln not in set(sick)]
        self._sick = set(sick)
        self._lock = threading.Lock()

    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def healthy_lanes(self):
        with self._lock:
            return list(self._healthy)

    def run_batch(self, pixels, dims, lane=0):
        with self._lock:
            self.calls.append((pixels.shape[0], lane))
            if lane in self._sick:
                if lane in self._healthy:
                    self._healthy.remove(lane)
                raise LaneQuarantined(lane, "deadline")
        mask = (pixels > 0).astype(np.uint8)
        return mask, np.ones(pixels.shape[0], bool)


class _TraceAwareExec:
    """Trace-aware fake for the lane-credit contract: ``run_batch``
    mirrors the real executor — it flags CPU-fallback service on the
    chunk's own trace, and can flip ``degraded`` immediately after a
    lane-served dispatch (the interleaving the credit logic must not
    misread as a fallback serve)."""

    supports_trace = True
    lane_count = 2
    max_batch = 2

    def __init__(self, serve_by_fallback=False, flip_degraded_after=False):
        self.cfg = SimpleNamespace(canvas=16, min_dim=4)
        self.buckets = (1, 2)
        self.degraded = False
        self._serve_by_fallback = serve_by_fallback
        self._flip = flip_degraded_after

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run_batch(self, pixels, dims, lane=0, trace=None):
        if self._serve_by_fallback:
            self.degraded = True
            if trace is not None:
                trace.served_by_fallback = True
        mask = (pixels > 0).astype(np.uint8)
        out = mask, np.ones(pixels.shape[0], bool)
        if self._flip:
            self.degraded = True  # the racing last-lane quarantine
        return out


class TestBatcherRedispatch:
    def test_quarantined_chunk_requeues_to_healthy_lane(self):
        ex = QuarantiningExecutor(buckets=(1, 2), lanes=2, sick=(1,))
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = _reqs(2)
        b._execute_chunk(reqs, 1)  # straight onto the sick lane
        for r in reqs:
            assert r.done.is_set() and r.error is None
            assert r.lane == 0  # served by the survivor
            assert r.requeues == 1
            assert r.mask.shape == r.dims
        # first attempt on 1, re-dispatch on 0
        assert [c[1] for c in ex.calls] == [1, 0]

    def test_fanout_skips_quarantined_lanes(self):
        ex = QuarantiningExecutor(buckets=(1, 2, 4), lanes=4, sick=(1,))
        ex._healthy = [0, 2, 3]  # lane 1 already out
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        assert b.healthy_lanes() == [0, 2, 3]
        # healthy fleet capacity: 3 lanes x largest bucket 4
        assert b.effective_max_batch() == 12
        reqs = _reqs(6)
        b.execute(reqs)
        # 6 over 3 healthy lanes -> chunk 2 -> lanes 0, 2, 3; never lane 1
        assert sorted(c[1] for c in ex.calls) == [0, 2, 3]
        assert all(r.error is None for r in reqs)
        assert set(b.stats()["lane_batches"]) == {"0", "2", "3"}

    def test_requeue_budget_bounds_the_loop(self):
        # every lane quarantines and the fake (unlike the real executor)
        # never degrades to a fallback: the riders must FAIL after the
        # budget, not spin forever
        ex = QuarantiningExecutor(buckets=(1, 2), lanes=2, sick=(0, 1))
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = _reqs(2)
        b._execute_chunk(reqs, 0)
        for r in reqs:
            assert r.done.is_set()
            # the internal routing signal never reaches a rider: the
            # budget failure is an operator-readable wrapper
            assert isinstance(r.error, RuntimeError)
            assert not isinstance(r.error, LaneQuarantined)
            assert "flapping" in str(r.error)
            assert isinstance(r.error.__cause__, LaneQuarantined)
        # bounded: lanes()+1 = 3 dispatch attempts at most
        assert len(ex.calls) <= 3

    def test_window_capacity_tracks_healthy_set(self):
        ex = QuarantiningExecutor(buckets=(1, 2, 4), lanes=4)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        assert b.effective_max_batch() == 16
        with ex._lock:
            ex._healthy = [0]
        assert b.effective_max_batch() == 4
        with ex._lock:
            ex._healthy = [0, 1, 2, 3]
        assert b.effective_max_batch() == 16  # reinstatement grows it back

    def test_lane_credit_follows_the_chunk_not_the_degraded_flag(self):
        # (a) the chunk ran ON a lane; a concurrent last-lane quarantine
        # flipped `degraded` right after the dispatch returned — the
        # credit must still land (the real executor already counted
        # serving_lane_batches_total for it). Re-reading `degraded` at
        # credit time miscounted exactly this interleaving.
        ex = _TraceAwareExec(flip_degraded_after=True)
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = _reqs(1)
        b._execute_chunk(reqs, 0)
        assert reqs[0].error is None
        assert reqs[0].lane == 0
        assert b.stats()["lane_batches"] == {"0": 1}
        # (b) the chunk was served by the process-wide CPU fallback; the
        # executor flags that on the chunk's OWN trace — no lane ran it,
        # so no lane is credited and the rider's payload reports lane null
        ex = _TraceAwareExec(serve_by_fallback=True)
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = _reqs(1)
        b._execute_chunk(reqs, 0)
        assert reqs[0].error is None
        assert reqs[0].lane is None
        assert b.stats()["lane_batches"] == {}


# -- lane selectors in the fault plan ---------------------------------------


class TestFaultPlanLaneSelector:
    def _plan(self, **rule):
        from nm03_capstone_project_tpu.resilience import FaultPlan

        return FaultPlan.from_spec(
            json.dumps({"seed": 7, "faults": [{"site": "dispatch", **rule}]})
        )

    def test_lane_selected_rule_fires_only_on_that_lane(self):
        plan = self._plan(kind="hang", lane=2)
        assert plan.fire("dispatch", lane=0) is None
        assert plan.fire("dispatch", lane=None) is None  # batch drivers
        hit = plan.fire("dispatch", lane=2)
        assert hit is not None and hit.kind == "hang"

    def test_lane_rule_with_count_budget(self):
        plan = self._plan(kind="transient", lane=1, count=1)
        assert plan.fire("dispatch", lane=1) is not None
        assert plan.fire("dispatch", lane=1) is None  # budget spent

    def test_lane_keyed_rate_draw_is_schedule_independent(self):
        spec = {"kind": "transient", "rate": 0.5, "lane": 3}
        a = [
            self._plan(**spec)._draw(0, self._plan(**spec).rules[0],
                                     None, None, i, 3)
            for i in range(32)
        ]
        b = [
            self._plan(**spec)._draw(0, self._plan(**spec).rules[0],
                                     None, None, i, 3)
            for i in range(32)
        ]
        assert a == b and True in a and False in a

    def test_lane_only_skips_generic_rules_and_their_budgets(self):
        # the probation-probe contract: a canary consults ONLY rules that
        # explicitly select its lane — generic dispatch rules keep their
        # after/count budgets for the request traffic they were written
        # against (second-review finding)
        from nm03_capstone_project_tpu.resilience import FaultPlan

        plan = FaultPlan.from_spec(json.dumps({
            "seed": 7,
            "faults": [
                {"site": "dispatch", "kind": "transient", "count": 1},
                {"site": "dispatch", "kind": "hang", "lane": 2, "count": 1},
            ],
        }))
        # probes on lane 1: no lane-selected rule matches, and the generic
        # transient rule is neither fired nor has its ordinal advanced
        for _ in range(5):
            assert plan.fire("dispatch", lane=1, lane_only=True) is None
        assert plan.rules[0]._seen == 0 and plan.rules[0]._fired == 0
        # a probe on the WEDGED lane still eats its targeted rule
        hit = plan.fire("dispatch", lane=2, lane_only=True)
        assert hit is not None and hit.kind == "hang"
        # the generic budget is intact for request traffic
        assert plan.fire("dispatch", lane=0).kind == "transient"

    def test_unknown_key_still_rejected(self):
        from nm03_capstone_project_tpu.resilience import FaultPlan

        with pytest.raises(ValueError, match="unknown"):
            FaultPlan.from_spec(json.dumps({
                "faults": [{"site": "dispatch", "kind": "hang", "lan": 2}]
            }))

# -- the real executor under lane-targeted chaos ----------------------------


def _hang_plan(*lanes, count=1, seed=5, hang_s=20.0):
    from nm03_capstone_project_tpu.resilience import FaultPlan

    faults = [
        {"site": "dispatch", "kind": "hang", "lane": ln, "hang_s": hang_s,
         **({"count": count} if count else {})}
        for ln in lanes
    ]
    return FaultPlan.from_spec(json.dumps({"seed": seed, "faults": faults}))


class _RunObs(_Obs):
    """_Obs plus the RunContext helper methods the supervisor/executor call."""

    def retry(self, **kw):
        return self.events.emit("retry", **kw)

    def degraded(self, cause, **kw):
        self.registry.counter(
            "pipeline_degraded_total", help="", cause=cause
        ).inc()
        return self.events.emit("degraded", level="WARNING", cause=cause, **kw)

    def fault_injected(self, **kw):
        return self.events.emit("fault_injected", **kw)


def _exec(plan, lanes=2, probe_s=0.2, obs=None, timeout_s=0.8):
    from nm03_capstone_project_tpu.resilience import ResilienceConfig
    from nm03_capstone_project_tpu.serving.executor import WarmExecutor

    from nm03_capstone_project_tpu.config import PipelineConfig

    return WarmExecutor(
        PipelineConfig(canvas=CANVAS),
        buckets=(1,),
        resilience=ResilienceConfig(
            retry_max=1, retry_backoff_s=0.01, dispatch_timeout_s=timeout_s
        ),
        obs=obs if obs is not None else _RunObs(),
        fault_plan=plan,
        lanes=lanes,
        lane_probe_interval_s=probe_s,
    )


def _batch1():
    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    img = phantom_slice(CANVAS, CANVAS, seed=3).astype(np.float32)
    return img[None], np.asarray([[CANVAS, CANVAS]], np.int32)


@pytest.fixture
def flight_dir(tmp_path):
    from nm03_capstone_project_tpu.obs import flightrec

    flightrec.configure(str(tmp_path))
    try:
        yield tmp_path
    finally:
        flightrec.configure(None)


class TestWarmExecutorFaultDomains:
    def test_wedge_quarantines_lane_and_probe_reinstates(self, flight_dir):
        obs = _RunObs()
        ex = _exec(_hang_plan(1), obs=obs)
        ex.warmup()
        px, dm = _batch1()
        m0, _ = ex.run_batch(px, dm, lane=0)
        with pytest.raises(LaneQuarantined) as ei:
            ex.run_batch(px, dm, lane=1)
        assert ei.value.lane == 1 and ei.value.cause == "deadline"
        assert ex.fleet.state(1) == QUARANTINED
        # ONE lane out: no process degradation, capacity halves, the
        # quarantine auto-dumped the flight rings
        assert not ex.degraded
        assert ex.lanes_ready == 1 and ex.capacity == 0.5
        assert ex.quarantined_count == 1
        assert ex.healthy_lanes() == [0]
        dumps = glob.glob(
            str(flight_dir / "nm03_flight_*lane1_quarantine_deadline*.json")
        )
        assert dumps, os.listdir(flight_dir)
        # lane 0 keeps serving the identical result meanwhile
        m_ok, _ = ex.run_batch(px, dm, lane=0)
        np.testing.assert_array_equal(m0, m_ok)
        # the probation probe (count=1 budget is spent) reinstates lane 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not ex.fleet.is_healthy(1):
            time.sleep(0.05)
        assert ex.fleet.is_healthy(1), ex.fleet.snapshot()
        assert ex.lanes_ready == 2 and ex.capacity == 1.0
        m1, _ = ex.run_batch(px, dm, lane=1)
        np.testing.assert_array_equal(m0, m1)
        assert (
            obs.registry.get("serving_lane_reinstated_total", lane="1").value
            == 1
        )
        assert obs.events.of("lane_quarantined") and obs.events.of(
            "lane_reinstated"
        )
        # the process-wide ladder never engaged
        assert obs.registry.get("pipeline_degraded_total", cause="deadline") is None
        assert not obs.events.of("degraded")

    def test_persistent_wedge_fails_probe_and_stays_out(self):
        obs = _RunObs()
        # no count: the lane hangs EVERY dispatch, canaries included
        ex = _exec(_hang_plan(1, count=0, hang_s=5.0), obs=obs, timeout_s=0.5)
        ex.warmup()
        px, dm = _batch1()
        with pytest.raises(LaneQuarantined):
            ex.run_batch(px, dm, lane=1)
        # wait out at least one full probe round
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            c = obs.registry.get(
                "serving_lane_quarantines_total", lane="1", cause="probe_failed"
            )
            if c is not None and c.value >= 1:
                break
            time.sleep(0.05)
        assert c is not None and c.value >= 1, "probe never failed the canary"
        assert ex.fleet.state(1) in (QUARANTINED, PROBATION)
        assert ex.lanes_ready == 1 and not ex.degraded
        # stop the prober before teardown: a daemon canary logging after
        # pytest closes its capture is noise, not signal
        with ex._lock:
            ex._degraded = True

    def test_all_lanes_wedged_trips_cpu_fallback(self, flight_dir):
        obs = _RunObs()
        ex = _exec(_hang_plan(0, 1), obs=obs, probe_s=60.0)
        ex.warmup()
        px, dm = _batch1()
        with pytest.raises(LaneQuarantined):
            ex.run_batch(px, dm, lane=0)
        assert not ex.degraded  # one healthy lane left
        with pytest.raises(LaneQuarantined):
            ex.run_batch(px, dm, lane=1)
        # the LAST lane went: the one-way PR-3 last resort
        assert ex.degraded and ex.degraded_cause == "deadline"
        assert ex.capacity == 0.0 and ex.lanes_ready == 0
        assert (
            obs.registry.get("pipeline_degraded_total", cause="deadline").value
            == 1
        )
        (ev,) = obs.events.of("degraded")
        assert ev["site"] == "serve_fleet" and ev["lanes"] == 2
        assert glob.glob(str(flight_dir / "nm03_flight_*degraded_deadline*"))
        # dispatches keep answering via the CPU fallback, mask-identical
        m_cpu, conv = ex.run_batch(px, dm, lane=0)
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        ref = process_slice(
            jnp.asarray(px[0]), jnp.asarray(dm[0]), PipelineConfig(canvas=CANVAS)
        )
        np.testing.assert_array_equal(
            np.asarray(m_cpu[0]), np.asarray(ref["mask"])
        )

    def test_no_fallback_cpu_fails_fast_when_all_lanes_gone(self):
        from nm03_capstone_project_tpu.resilience import ResilienceConfig
        from nm03_capstone_project_tpu.resilience.policy import DeadlineExceeded
        from nm03_capstone_project_tpu.serving.executor import WarmExecutor

        from nm03_capstone_project_tpu.config import PipelineConfig

        ex = WarmExecutor(
            PipelineConfig(canvas=CANVAS),
            buckets=(1,),
            resilience=ResilienceConfig(
                retry_max=1, retry_backoff_s=0.01, dispatch_timeout_s=0.5,
                fallback_cpu=False,
            ),
            obs=_RunObs(),
            fault_plan=_hang_plan(0, hang_s=5.0),
            lanes=1,
            lane_probe_interval_s=60.0,
        )
        ex.warmup()
        px, dm = _batch1()
        with pytest.raises(LaneQuarantined):
            ex.run_batch(px, dm, lane=0)
        assert ex.degraded
        with pytest.raises(DeadlineExceeded, match="fallback is disabled"):
            ex.run_batch(px, dm, lane=0)

# -- the full request path, in process --------------------------------------


def _expected_mask_pixels(img: np.ndarray) -> int:
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

    out = process_slice(
        jnp.asarray(img.astype(np.float32)),
        jnp.asarray([img.shape[0], img.shape[1]], jnp.int32),
        PipelineConfig(canvas=CANVAS),
    )
    return int(np.count_nonzero(np.asarray(out["mask"])))


class TestServingAppFaultDomains:
    def _app(self, plan, lanes=2, probe_s=0.2, max_wait_s=0.1):
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.resilience import ResilienceConfig
        from nm03_capstone_project_tpu.serving.server import ServingApp

        return ServingApp(
            cfg=PipelineConfig(canvas=CANVAS),
            queue_capacity=64,
            buckets=(1, 2),
            max_wait_s=max_wait_s,
            request_timeout_s=120.0,
            resilience=ResilienceConfig(
                retry_max=1, retry_backoff_s=0.01, dispatch_timeout_s=1.0
            ),
            fault_plan=plan,
            lanes=lanes,
            lane_probe_interval_s=probe_s,
        )

    def test_riders_survive_a_lane_wedge_and_lane_comes_back(self):
        """The in-process acceptance drill: one lane wedges under
        concurrent traffic; every request still answers 200-equivalent
        with the healthy-run mask, the wedge is one quarantine (not a
        process degradation), /readyz stays ready at reduced capacity,
        and probation returns the fleet to full strength."""
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice

        app = self._app(_hang_plan(1, hang_s=10.0))
        app.start()
        try:
            img = phantom_slice(CANVAS, CANVAS, seed=0)
            want = _expected_mask_pixels(img)
            results, errors = [], []
            lock = threading.Lock()
            barrier = threading.Barrier(6)

            def one():
                barrier.wait(timeout=30)
                try:
                    p = app.segment(img, render=False)
                    with lock:
                        results.append(p)
                except BaseException as e:  # noqa: BLE001 — the assert below
                    with lock:
                        errors.append(e)

            threads = [threading.Thread(target=one) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert len(results) == 6
            for p in results:
                assert p["mask_pixels"] == want
                assert p["degraded"] is False
            # the wedged chunk's riders outlived lane 1 via a requeue hop
            assert any(p["requeues"] >= 1 for p in results), results
            assert (
                app.registry.get(
                    "serving_lane_quarantines_total", lane="1", cause="deadline"
                ).value
                == 1
            )
            # partial capacity never flipped readiness
            assert app.ready
            assert app.registry.get("pipeline_degraded_total", cause="deadline") is None
            # probation heals the fleet
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and app.executor.lanes_ready < 2:
                time.sleep(0.05)
            st = app.status()
            assert st["lanes"]["ready"] == 2 and st["capacity"] == 1.0
            assert st["lanes"]["quarantined"] == 0
            assert (
                app.registry.get("serving_lane_reinstated_total", lane="1").value
                == 1
            )
            # and the healed lane serves the identical mask
            p = app.segment(img, render=False)
            assert p["mask_pixels"] == want
        finally:
            app.begin_drain(reason="test")
            app.close()

    def test_all_lanes_wedged_serves_from_cpu_and_flips_ready(self):
        """The last-resort drill: EVERY lane wedges; the request still
        answers (CPU fallback, identical mask), /readyz flips not-ready,
        and the process-wide degradation counts exactly once."""
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice

        app = self._app(_hang_plan(0, 1, hang_s=10.0), probe_s=60.0)
        app.start()
        try:
            img = phantom_slice(CANVAS, CANVAS, seed=1)
            want = _expected_mask_pixels(img)
            # one request walks the whole ladder: lane wedge -> requeue ->
            # other lane wedge -> all-quarantined -> CPU fallback answers
            p = app.segment(img, render=False)
            assert p["mask_pixels"] == want
            assert p["degraded"] is True and p["requeues"] >= 1
            assert not app.ready
            st = app.status()
            assert st["degraded"] and st["degraded_cause"] == "deadline"
            assert st["capacity"] == 0.0 and st["lanes"]["quarantined"] == 2
            assert (
                app.registry.get("pipeline_degraded_total", cause="deadline").value
                == 1
            )
            # still answering (correct-but-slower is the contract)
            p2 = app.segment(img, render=False)
            assert p2["mask_pixels"] == want
        finally:
            app.begin_drain(reason="test")
            app.close()

# -- the chaos acceptance drill (real nm03-serve subprocess) ----------------


def _post(url, body, headers, timeout=90.0):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class _ReadyzPoller:
    """Samples /readyz through the drill: HTTP statuses + payloads."""

    def __init__(self, base):
        self.base = base
        self.samples = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(0.05):
            try:
                req = urllib.request.Request(self.base + "/readyz", method="GET")
                try:
                    with urllib.request.urlopen(req, timeout=5) as r:
                        self.samples.append((r.status, json.loads(r.read())))
                except urllib.error.HTTPError as e:
                    self.samples.append((e.code, json.loads(e.read() or b"{}")))
            except Exception:  # noqa: BLE001 — transient socket noise
                pass

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


class TestChaosAcceptanceDrill:
    def test_lane2_wedge_under_load_partial_capacity_then_reinstated(
        self, tmp_path
    ):
        """The ISSUE 8 acceptance bar, end to end in a real process:
        ``nm03-serve --lanes 4`` with a fault plan that deterministically
        wedges lane 2's first dispatch, under 16-way concurrent load —
        every request answers 200 with the healthy-run mask, ``/readyz``
        never leaves 200 and reports reduced capacity while the lane is
        out, the quarantine auto-dumps a flight record naming the wedged
        riders, probation reinstates the lane, and the process-wide CPU
        fallback is NOT tripped (asserted via the labeled lane metrics)."""
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice

        port_file = tmp_path / "port"
        metrics = tmp_path / "metrics.json"
        flight = tmp_path / "flight"
        flight.mkdir()
        plan = json.dumps({
            "seed": 5,
            "faults": [{
                "site": "dispatch", "kind": "hang", "lane": 2,
                "count": 1, "hang_s": 30.0,
            }],
        })
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1", "--lanes", "4",
                "--max-wait-ms", "30", "--heartbeat-s", "0",
                "--metrics-out", str(metrics),
                "--flight-dir", str(flight),
                "--dispatch-timeout-s", "1.0",
                "--retry-max", "1", "--retry-backoff-s", "0.01",
                "--lane-probe-interval-s", "2.0",
                "--fault-plan", plan,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        poller = None
        try:
            deadline = time.monotonic() + 300
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.2)
            assert port_file.exists(), "server never became ready"
            base = f"http://127.0.0.1:{int(port_file.read_text())}"
            img = phantom_slice(CANVAS, CANVAS, seed=1)
            want = _expected_mask_pixels(img)
            body = img.astype("<f4").tobytes()
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Height": str(CANVAS),
                "X-Nm03-Width": str(CANVAS),
            }
            poller = _ReadyzPoller(base).start()
            results = []
            lock = threading.Lock()

            def one(i):
                s, p = _post(
                    base + "/v1/segment?output=mask",
                    body,
                    {**headers, "X-Nm03-Request-Id": f"drill-{i:03d}"},
                )
                with lock:
                    results.append((s, p))

            threads = [
                threading.Thread(target=one, args=(i,)) for i in range(16)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            # the acceptance bar: NO non-shed error, masks bit-identical
            assert len(results) == 16
            assert all(s == 200 for s, _ in results), [
                (s, p) for s, p in results if s != 200
            ]
            assert all(p["mask_pixels"] == want for _, p in results)
            assert all(p["degraded"] is False for _, p in results)
            # wedged riders outlived lane 2 via a requeue hop
            assert any(p["requeues"] >= 1 for _, p in results)
            # wait for probation to reinstate lane 2 (probe every 2s)
            deadline = time.monotonic() + 60
            healed = False
            while time.monotonic() < deadline and not healed:
                time.sleep(0.2)
                with lock:
                    healed = any(
                        p.get("lanes", {}).get("ready") == 4
                        and p.get("lanes", {}).get("quarantined") == 0
                        and any(
                            s.get("lanes", {}).get("quarantined", 0) >= 1
                            for _, s in poller.samples
                        )
                        for _, p in poller.samples[-3:]
                    )
            poller.stop()
            # /readyz NEVER left 200, and the partial-capacity plateau was
            # observable while lane 2 sat in quarantine
            statuses = {s for s, _ in poller.samples}
            assert statuses == {200}, statuses
            dips = [
                p for _, p in poller.samples
                if p.get("lanes", {}).get("quarantined", 0) >= 1
            ]
            assert dips, "quarantine window never observed on /readyz"
            assert all(p["capacity"] == 0.75 for p in dips)
            assert all(p["ready"] for p in dips)
            final = poller.samples[-1][1]
            assert final["lanes"]["ready"] == 4, final["lanes"]
            assert final["capacity"] == 1.0
            # the quarantine auto-dump names the wedged riders
            dumps = glob.glob(
                str(flight / "nm03_flight_*lane2_quarantine_deadline*.json")
            )
            assert dumps, os.listdir(flight)
            assert "drill-" in open(dumps[0]).read()
            # a healed fleet serves a second wave cleanly
            wave2 = [
                _post(base + "/v1/segment?output=mask", body, headers)
                for _ in range(4)
            ]
            assert all(s == 200 and p["mask_pixels"] == want for s, p in wave2)
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if poller is not None:
                poller.stop()
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        # the labeled-metric assertions: lane 2 was quarantined exactly
        # once, reinstated, and ended HEALTHY; the fleet ended at 4 ready;
        # the process-wide degradation never tripped
        res = subprocess.run(
            [
                sys.executable, CHECKER,
                "--metrics", str(metrics),
                "--expect-gauge", "serving_lanes_ready=4",
                "--expect-gauge", "serving_lane_state{lane=2}=0",
                "--expect-counter", "serving_lane_quarantines_total{lane=2}=1",
                "--expect-counter", "serving_lane_reinstated_total{lane=2}=1",
                "--expect-gauge", "serving_degraded=0",
                "--expect-counter", "serving_requests_total=20",
            ],
            capture_output=True, text=True, timeout=60,
        )
        assert res.returncode == 0, res.stderr
        snap = json.loads(metrics.read_text())
        names = {m["name"] for m in snap["metrics"]}
        assert "pipeline_degraded_total" not in names  # fallback never fired


# -- the labeled expectation hooks in check_telemetry -----------------------


class TestLabeledExpectations:
    def _snapshot(self, tmp_path):
        snap = {
            "schema": "nm03.metrics.v1", "run_id": "r", "git_sha": "g",
            "created_unix": 1.0,
            "metrics": [
                {"name": "serving_lane_state", "type": "gauge",
                 "labels": {"lane": "0"}, "value": 0},
                {"name": "serving_lane_state", "type": "gauge",
                 "labels": {"lane": "2"}, "value": 2},
                {"name": "serving_lane_quarantines_total", "type": "counter",
                 "labels": {"lane": "2", "cause": "deadline"}, "value": 1},
            ],
        }
        p = tmp_path / "m.json"
        p.write_text(json.dumps(snap))
        return p

    def _run(self, p, *args):
        return subprocess.run(
            [sys.executable, CHECKER, "--metrics", str(p), *args],
            capture_output=True, text=True, timeout=60,
        )

    def test_labeled_gauge_green(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(p, "--expect-gauge", "serving_lane_state{lane=0}=0")
        assert r.returncode == 0, r.stderr

    def test_labeled_gauge_wrong_value_red(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(p, "--expect-gauge", "serving_lane_state{lane=2}=0")
        assert r.returncode == 1 and "expected == 0" in r.stderr

    def test_labeled_gauge_absent_series_red(self, tmp_path):
        # zero-for-absent would make "lane 5 healthy" pass on a fleet that
        # never reported lane 5: absence must be a DRIFT
        p = self._snapshot(tmp_path)
        r = self._run(p, "--expect-gauge", "serving_lane_state{lane=5}=0")
        assert r.returncode == 1 and "no series matches" in r.stderr

    def test_labeled_counter_green_and_red(self, tmp_path):
        p = self._snapshot(tmp_path)
        ok = self._run(
            p, "--expect-counter",
            "serving_lane_quarantines_total{lane=2,cause=deadline}=1",
        )
        assert ok.returncode == 0, ok.stderr
        bad = self._run(
            p, "--expect-counter", "serving_lane_quarantines_total{lane=0}=1"
        )
        assert bad.returncode == 1 and "no series matches" in bad.stderr

    def test_unlabeled_sum_still_works(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(p, "--expect-gauge", "serving_lane_state=2")
        assert r.returncode == 0, r.stderr

    def test_malformed_selector_is_usage_error(self, tmp_path):
        p = self._snapshot(tmp_path)
        r = self._run(p, "--expect-gauge", "serving_lane_state{=2")
        assert r.returncode == 2
