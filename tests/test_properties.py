"""Property-based kernel-vs-oracle tests (hypothesis).

The fixed-case oracle tests (test_median_sharpen, test_morphology, ...)
pin known inputs; these throw randomized shapes, dims, and data at the same
contracts so shape-edge and clamp-edge bugs can't hide between the
hand-picked cases. Sizes are kept small and example counts modest: every
distinct shape costs a jit compile on the CPU backend.
"""

import numpy as np
import pytest

# optional dependencies (pyproject [test] extra): without them this module
# must SKIP, not break collection for the whole suite
ndi = pytest.importorskip("scipy.ndimage")
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from nm03_capstone_project_tpu.ops.elementwise import clip_intensity, normalize
from nm03_capstone_project_tpu.ops.median import vector_median_filter
from nm03_capstone_project_tpu.ops.morphology import dilate, erode
from nm03_capstone_project_tpu.ops.neighborhood import extend_edges

CANVAS = 32  # one static shape -> one compile, shared by all examples

_dims = st.tuples(
    st.integers(min_value=1, max_value=CANVAS),
    st.integers(min_value=1, max_value=CANVAS),
)


def _random_canvas(data, h, w):
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    px = np.zeros((CANVAS, CANVAS), np.float32)
    px[:h, :w] = rng.normal(size=(h, w)).astype(np.float32)
    return px


@settings(max_examples=30, deadline=None)
@given(data=st.data(), hw=_dims)
def test_extend_edges_matches_bruteforce_clamp(data, hw):
    h, w = hw
    px = _random_canvas(data, h, w)
    out = np.asarray(extend_edges(px, np.asarray([h, w], np.int32)))
    rows = np.minimum(np.arange(CANVAS), h - 1)
    cols = np.minimum(np.arange(CANVAS), w - 1)
    want = px[np.ix_(rows, cols)]
    np.testing.assert_array_equal(out, want)


@settings(max_examples=15, deadline=None)
@given(data=st.data(), window=st.sampled_from([3, 5, 7]))
def test_median_matches_scipy_on_full_canvas(data, window):
    px = _random_canvas(data, CANVAS, CANVAS)
    got = np.asarray(vector_median_filter(px, window))
    # ops pad with edge replication; scipy 'nearest' is the same contract
    want = ndi.median_filter(px, size=window, mode="nearest")
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


@settings(max_examples=20, deadline=None)
@given(
    data=st.data(),
    window=st.sampled_from([3, 5, 7]),
    dtype=st.sampled_from(["uint8", "float32"]),
)
def test_pruned_selection_median_matches_jnp_median(data, window, dtype):
    """ISSUE 2 satellite: the pruned selection network must equal the
    jnp.median-based reference on random uint8/f32 images for sizes 3/5/7.

    The reference materializes every window (shifted_stack) and takes
    jnp.median over the window axis — a completely independent formulation
    (a sort, not a comparator network), so agreement pins the network's
    rank selection, its liveness pruning, and the shift/domain bookkeeping
    of the plan executor at once.
    """
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.ops.neighborhood import (
        shifted_stack,
        window_offsets,
    )

    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    if dtype == "uint8":
        px = rng.integers(0, 256, (CANVAS, CANVAS)).astype(np.uint8)
    else:
        px = (rng.random((CANVAS, CANVAS)) * 4000.0).astype(np.float32)
    got = np.asarray(vector_median_filter(px, window))
    stack = shifted_stack(jnp.asarray(px), window_offsets(window), pad_mode="edge")
    want = np.asarray(jnp.median(stack, axis=0))
    np.testing.assert_array_equal(got.astype(np.float64), want.astype(np.float64))


@settings(max_examples=10, deadline=None)
@given(data=st.data(), hw=_dims)
def test_fused_render_pair_is_pixel_exact(data, hw):
    """ISSUE 2 satellite: the fused render must be pixel-identical to the
    unfused pair on random images, masks and true dims."""
    import dataclasses

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.render.render import render_pair

    h, w = hw
    px = _random_canvas(data, h, w) * 900.0
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    mask = np.zeros((CANVAS, CANVAS), np.uint8)
    mask[:h, :w] = (rng.random((h, w)) < 0.4).astype(np.uint8)
    dims = np.asarray([h, w], np.int32)
    # one static render size so every example shares a compile
    cfg = PipelineConfig(render_size=64)
    cfg_unfused = dataclasses.replace(cfg, render_fused=False)
    g1, s1 = render_pair(px, mask, dims, cfg)
    g2, s2 = render_pair(px, mask, dims, cfg_unfused)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))


@settings(max_examples=15, deadline=None)
@given(
    data=st.data(),
    size=st.sampled_from([3, 5]),
    shape=st.sampled_from(["cross", "box"]),
    op=st.sampled_from(["dilate", "erode"]),
)
def test_morphology_matches_scipy_binary(data, size, shape, op):
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    mask = (rng.random((CANVAS, CANVAS)) < 0.3).astype(np.uint8)
    fn = dilate if op == "dilate" else erode
    got = np.asarray(fn(mask, size, shape)).astype(bool)
    if shape == "box":
        structure = np.ones((size, size), bool)
    else:  # cross: city-block radius size//2
        r = size // 2
        yy, xx = np.mgrid[-r : r + 1, -r : r + 1]
        structure = (np.abs(yy) + np.abs(xx)) <= r
    sfn = ndi.binary_dilation if op == "dilate" else ndi.binary_erosion
    # outside-image counts as background for both ops (ops/morphology.py)
    want = sfn(mask.astype(bool), structure=structure, border_value=0)
    np.testing.assert_array_equal(got, want)


@settings(max_examples=10, deadline=None)
@given(data=st.data())
def test_region_growing_is_exact_seeded_flood_fill(data):
    # the SeededRegionGrowing contract: exactly the band-valued pixels
    # 4-connected to a seed through the band (no more, no less)
    from nm03_capstone_project_tpu.ops.region_growing import region_grow

    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    px = rng.random((CANVAS, CANVAS)).astype(np.float32)
    seeds = np.zeros((CANVAS, CANVAS), bool)
    for _ in range(data.draw(st.integers(1, 4))):
        seeds[rng.integers(0, CANVAS), rng.integers(0, CANVAS)] = True
    lo, hi = 0.3, 0.8
    got = np.asarray(region_grow(px, seeds, lo, hi)[0]).astype(bool)
    from tests.oracles import region_grow_oracle

    want = region_grow_oracle(px, seeds, lo, hi).astype(bool)
    np.testing.assert_array_equal(got, want)


@settings(max_examples=6, deadline=None)
@given(data=st.data())
def test_region_growing_3d_is_exact_seeded_flood_fill(data):
    # 6-connected flood fill through the band, across slices
    from nm03_capstone_project_tpu.ops.volume import region_grow_3d

    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    vol = rng.random((8, 16, 16)).astype(np.float32)
    seeds = np.zeros_like(vol, bool)
    for _ in range(data.draw(st.integers(1, 3))):
        seeds[
            rng.integers(0, 8), rng.integers(0, 16), rng.integers(0, 16)
        ] = True
    lo, hi = 0.3, 0.8
    got = np.asarray(
        region_grow_3d(vol, seeds, lo, hi, block_iters=8, max_iters=256)[0]
    ).astype(bool)
    from tests.oracles import region_grow_oracle

    want = region_grow_oracle(vol, seeds, lo, hi).astype(bool)
    np.testing.assert_array_equal(got, want)


@settings(max_examples=30, deadline=None)
@given(data=st.data(), hw=_dims)
def test_normalize_clip_stay_in_declared_range(data, hw):
    h, w = hw
    px = np.abs(_random_canvas(data, h, w)) * 5000.0
    out = np.asarray(
        clip_intensity(normalize(px, 0.5, 2.5, 0.0, 10000.0), 0.68, 4000.0)
    )
    assert np.isfinite(out).all()
    assert out.min() >= 0.68 - 1e-6 and out.max() <= 4000.0 + 1e-6


# ---------------------------------------------------------------------------
# Compressed-pixel codecs (data/codecs.py): pure host-side code, no jit cost,
# so these can afford arbitrary shapes per example.
# ---------------------------------------------------------------------------


@settings(max_examples=40, deadline=None)
@given(
    data=st.data(),
    hw=st.tuples(st.integers(1, 48), st.integers(1, 48)),
    kind=st.sampled_from(["noise", "runs", "gradient"]),
)
def test_rle_round_trip_any_content(data, hw, kind):
    from nm03_capstone_project_tpu.data import codecs

    h, w = hw
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    if kind == "noise":
        img = rng.integers(0, 65_536, (h, w), dtype=np.uint16)
    elif kind == "runs":
        img = np.repeat(
            rng.integers(0, 65_536, (h, 1), dtype=np.uint16), w, axis=1
        )
    else:
        img = (np.outer(np.arange(h), np.arange(w)) % 65_536).astype(np.uint16)
    dec = codecs.rle_decode_frame(codecs.rle_encode_frame(img), h, w, 2)
    np.testing.assert_array_equal(dec, img)


@settings(max_examples=40, deadline=None)
@given(
    data=st.data(),
    hw=st.tuples(st.integers(1, 40), st.integers(1, 40)),
)
def test_jpeg_lossless_round_trip_any_content(data, hw):
    from nm03_capstone_project_tpu.data import codecs

    h, w = hw
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    # full-range noise maximizes diff magnitudes (exercises every SSSS
    # category incl. the no-extra-bits 16 case)
    img = rng.integers(0, 65_536, (h, w), dtype=np.uint16)
    dec = codecs.jpeg_lossless_decode(codecs.jpeg_lossless_encode(img))
    np.testing.assert_array_equal(dec, img)


@settings(max_examples=40, deadline=None)
@given(
    data=st.data(),
    hw=st.tuples(st.integers(1, 40), st.integers(1, 40)),
    kind=st.sampled_from(["noise", "runs", "constant", "gradient"]),
)
def test_jpegls_round_trip_any_content(data, hw, kind):
    """JPEG-LS encoder/decoder round trip under hypothesis: noise (regular
    mode, every Golomb k), runs (run mode + interruptions), constants
    (EOL-run + trailing-0xFF stuffed-pad edge), gradients (context spread)."""
    from nm03_capstone_project_tpu.data import codecs

    h, w = hw
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    if kind == "noise":
        img = rng.integers(0, 65_536, (h, w), dtype=np.uint16)
    elif kind == "runs":
        img = np.repeat(
            rng.integers(0, 65_536, (h, 1), dtype=np.uint16), w, axis=1
        )
    elif kind == "constant":
        img = np.full((h, w), data.draw(st.integers(0, 65_535)), np.uint16)
    else:
        img = (np.outer(np.arange(h), np.arange(w)) % 65_536).astype(np.uint16)
    dec = codecs.jpegls_decode(codecs.jpegls_encode(img))
    np.testing.assert_array_equal(dec, img)


@settings(max_examples=30, deadline=None)
@given(
    data=st.data(),
    hw=st.tuples(st.integers(1, 32), st.integers(1, 32)),
    near=st.integers(1, 7),
)
def test_jpegls_near_lossless_bound_holds(data, hw, near):
    """near>0 encode: every reconstructed sample within ±near of the
    source, for arbitrary content (T.87's near-lossless guarantee)."""
    from nm03_capstone_project_tpu.data import codecs

    h, w = hw
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31 - 1)))
    img = rng.integers(0, 65_536, (h, w), dtype=np.uint16)
    dec = codecs.jpegls_decode(codecs.jpegls_encode(img, near=near))
    err = np.abs(dec.astype(np.int64) - img.astype(np.int64))
    assert int(err.max()) <= near
