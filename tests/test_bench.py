"""bench.py contract tests (VERDICT round 1 items 1-2).

Round 1 shipped a silent TypeError in the CPU-baseline call site that forced
``vs_baseline`` to 1.0 on every successful TPU run. These tests pin the whole
reporting contract without hardware: the worker's measurement path runs for
real on the CPU backend (tiny shapes), and the orchestrator's composition
logic (headline selection, pallas checksum gating, vs_baseline ratio,
fallback JSON) runs against stubbed workers.
"""

import importlib.util
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

_BENCH_PATH = pathlib.Path(__file__).parents[1] / "bench.py"
_spec = importlib.util.spec_from_file_location("bench", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _emitted(capsys):
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert lines, "no JSON line emitted"
    return json.loads(lines[-1].removeprefix(bench._SENTINEL))


class TestWorker:
    @pytest.mark.slow
    def test_cpu_worker_measures_and_appends_sections(
        self, monkeypatch, capsys, tmp_path
    ):
        monkeypatch.setattr(bench, "BATCH", 2)
        monkeypatch.setattr(bench, "CANVAS", 64)
        out = tmp_path / "sections.jsonl"
        bench.worker("cpu", reps=1, want_pallas=False, want_stages=False,
                     out_path=str(out))
        res = _emitted(capsys)
        assert res["backend"] == "cpu"
        assert res["xla_tput"] > 0
        assert res["checksum"] > 0  # phantom lesion segmented
        # incremental sections file carries the same data (timeout recovery)
        merged = {}
        for line in out.read_text().splitlines():
            merged.update(json.loads(line))
        assert merged["xla_tput"] == res["xla_tput"]

    @pytest.mark.slow
    def test_scan_chunk_leg_measures_and_checksums(self, monkeypatch, capsys):
        # the dispatch-amortized leg: chunk distinct batches per dispatch,
        # checksum = chunk x the single-batch checksum (rolled copies);
        # gated behind --scan so the shed path and the CPU baseline never
        # pay its compile
        import jax

        monkeypatch.setattr(bench, "BATCH", 2)
        monkeypatch.setattr(bench, "CANVAS", 64)
        monkeypatch.setattr(bench, "SCAN_CHUNK", 3)
        dev = jax.devices("cpu")[0]
        _, base_sum = bench._bench_on(dev, *bench._make_batch(2), reps=1)
        tput, checksum = bench._bench_scan_chunk(dev, 2, reps=1, chunk=3)
        assert tput > 0
        assert checksum == 3 * base_sum
        bench.worker("cpu", reps=1, want_pallas=False, want_stages=False,
                     out_path=None, want_scan=True)
        res = _emitted(capsys)
        assert res["scan_checksum_ok"] is True
        assert res["xla_scan_tput"] > 0
        # and OFF by default (the CPU-baseline / shed path)
        bench.worker("cpu", reps=1, want_pallas=False, want_stages=False,
                     out_path=None)
        assert "xla_scan_tput" not in _emitted(capsys)

    def test_probe_round_trip(self, capsys):
        bench.probe("cpu")
        assert _emitted(capsys)["backend"] == "cpu"

    @pytest.mark.slow
    def test_stage_times_fit_out_the_dispatch_floor(self, monkeypatch):
        # the two-batch fit must decompose ms_per_batch into a batch-linear
        # device_ms plus a constant dispatch_floor_ms, and attach an
        # achieved-GB/s roofline figure to the memory-bound stages
        # (VERDICT r2 weak item 3)
        monkeypatch.setattr(bench, "BATCH", 4)
        monkeypatch.setattr(bench, "STAGE_SMALL_BATCH", 2)
        monkeypatch.setattr(bench, "CANVAS", 64)
        import jax

        prof = bench._stage_times(jax.devices("cpu")[0], reps=2)
        assert prof["device_kind"]
        stages = prof["stages"]
        assert set(stages) == set(bench._STAGE_BOUND)
        for name, s in stages.items():
            assert s["device_ms"] + s["dispatch_floor_ms"] == pytest.approx(
                s["ms_per_batch"], abs=0.01
            )
            if name in bench._STAGE_MIN_BYTES and s["device_ms"] > 0:
                assert s["achieved_gbps"] > 0
        # share still sums to 1 over the real pipeline stages
        total = sum(
            s["share"] for n, s in stages.items() if n != "region_grow_jump"
        )
        assert total == pytest.approx(1.0, abs=0.02)

    @pytest.mark.slow
    def test_batch_sweep_keeps_the_best(self, monkeypatch, capsys, tmp_path):
        monkeypatch.setattr(bench, "CANVAS", 64)
        out = tmp_path / "sections.jsonl"
        bench.worker(
            "cpu",
            reps=1,
            want_pallas=False,
            want_stages=False,
            out_path=str(out),
            batches=(2, 4),
        )
        res = _emitted(capsys)
        assert set(res["xla_by_batch"]) == {"2", "4"}
        assert res["xla_batch"] in (2, 4)
        # by_batch entries are rounded for the record; the winner is not
        assert round(res["xla_tput"], 2) == max(res["xla_by_batch"].values())


class TestVolumeLegs:
    def test_volume_leg_measures(self, monkeypatch):
        # the 3D pipeline perf leg (VERDICT r3 item 5), tiny shapes
        import jax

        monkeypatch.setattr(bench, "VOLUME_DEPTH", 6)
        monkeypatch.setattr(bench, "CANVAS", 64)
        out = bench._bench_volume(jax.devices("cpu")[0], reps=1)
        assert out["ms_per_volume"] > 0
        assert out["checksum"] > 0  # the 3D lesion segmented
        assert out["depth"] == 6 and out["canvas"] == 64

    @pytest.mark.slow
    def test_zshard_scaling_curve_checksums_agree(self, monkeypatch, capsys):
        # every shard count must produce the identical mask checksum within
        # each path (z-shard 3D and dp 2D have different masks from each
        # other by design); the curves are informational on virtual devices
        monkeypatch.setattr(bench, "ZSHARD_DEPTH", 8)
        monkeypatch.setattr(bench, "ZSHARD_CANVAS", 64)
        bench.zshard_scaling()
        rec = _emitted(capsys)
        assert rec["checksum_ok"] is True
        assert set(rec["ms"]) == {"1", "2", "4", "8"}
        assert set(rec["dp_ms"]) == {"1", "2", "4", "8"}

    def test_compose_carries_volume_and_zshard(self, monkeypatch, capsys):
        monkeypatch.setattr(bench, "_PARTIAL_PATH", "/tmp/bench_partial_t.json")
        monkeypatch.setattr(bench, "_probe_until_healthy", lambda *a: True)
        monkeypatch.setattr(
            bench, "_run_measurement",
            lambda label, *a: {
                "backend": "tpu", "xla_tput": 10.0, "checksum": 1,
                "volume": {"ms_per_volume": 5.0},
            } if "accel" in label else {"backend": "cpu", "xla_tput": 2.0},
        )
        monkeypatch.setattr(
            bench, "_measure_zshard", lambda deadline: {"ms": {"1": 9.0}}
        )
        bench.main()
        out = _emitted(capsys)
        assert out["volume"] == {"ms_per_volume": 5.0}
        assert out["zshard_scaling"] == {"ms": {"1": 9.0}}


class TestOrchestrator:
    @pytest.fixture(autouse=True)
    def _isolated_partial_path(self, monkeypatch, tmp_path):
        # main() unlinks + rewrites the banked-record path; tests must never
        # touch the real results/bench_partial.json a chip run left behind
        self.partial_path = tmp_path / "bench_partial.json"
        monkeypatch.setattr(bench, "_PARTIAL_PATH", str(self.partial_path))
        # the REAL zshard worker is a multi-minute 8-virtual-device
        # subprocess (and actually runs now that the compile hub fixed the
        # seed's jax.shard_map AttributeError — it used to die instantly,
        # which is the only reason these tests ever looked fast); stub it
        # unless a test opts back in
        monkeypatch.setattr(bench, "_measure_zshard", lambda deadline: None)

    def _run_main(self, monkeypatch, capsys, accel, cpu, probe_ok=True,
                  vigil_ok=False):
        calls = []

        def fake_measure(label, worker_args, env_overrides, timeout_s):
            calls.append(label)
            return accel if "accel" in label else cpu

        monkeypatch.setattr(bench, "_probe_until_healthy", lambda *a: probe_ok)
        monkeypatch.setattr(bench, "_accel_vigil", lambda *a: vigil_ok)
        monkeypatch.setattr(bench, "_run_measurement", fake_measure)
        bench.main()
        return _emitted(capsys), calls

    def test_vs_baseline_is_the_ratio(self, monkeypatch, capsys):
        # the round-1 tuple bug forced this to 1.0; pin the real ratio
        out, _ = self._run_main(
            monkeypatch,
            capsys,
            accel={"backend": "tpu", "xla_tput": 100.0, "checksum": 7},
            cpu={"backend": "cpu", "xla_tput": 8.0, "checksum": 7},
        )
        assert out["value"] == 100.0
        assert out["vs_baseline"] == pytest.approx(12.5)
        assert out["backend"] == "tpu"
        assert "error" not in out

    def test_cpu_baseline_reruns_at_the_winning_batch(self, monkeypatch, capsys):
        # same-program ratio: the accel sweep winner's batch size is what
        # the cpu baseline must measure
        calls = {}

        def fake_measure(label, worker_args, env_overrides, timeout_s):
            calls[label] = list(worker_args)
            if "accel" in label:
                return {
                    "backend": "tpu",
                    "xla_tput": 100.0,
                    "xla_batch": 128,
                    "checksum": 7,
                }
            return {"backend": "cpu", "xla_tput": 8.0, "checksum": 7}

        monkeypatch.setattr(bench, "_probe_until_healthy", lambda *a: True)
        monkeypatch.setattr(bench, "_run_measurement", fake_measure)
        bench.main()
        out = _emitted(capsys)
        cpu_args = calls["cpu baseline"]
        assert cpu_args[cpu_args.index("--batches") + 1] == "128"
        assert out["batch"] == 128

    def test_pallas_wins_only_with_matching_checksum(self, monkeypatch, capsys):
        out, _ = self._run_main(
            monkeypatch,
            capsys,
            accel={
                "backend": "tpu",
                "xla_tput": 100.0,
                "checksum": 7,
                "pallas_tput": 150.0,
                "pallas_checksum_ok": True,
            },
            cpu={"backend": "cpu", "xla_tput": 10.0, "checksum": 7},
        )
        assert out["value"] == 150.0
        assert out["winning_path"] == "pallas"
        assert out["vs_baseline"] == pytest.approx(15.0)

    def test_pallas_checksum_mismatch_discarded(self, monkeypatch, capsys):
        out, _ = self._run_main(
            monkeypatch,
            capsys,
            accel={
                "backend": "tpu",
                "xla_tput": 100.0,
                "checksum": 7,
                "pallas_tput": 999.0,
                "pallas_checksum_ok": False,
            },
            cpu={"backend": "cpu", "xla_tput": 10.0, "checksum": 7},
        )
        assert out["value"] == 100.0
        assert out["winning_path"] == "xla"

    def test_accel_lost_falls_back_to_cpu_record(self, monkeypatch, capsys):
        out, calls = self._run_main(
            monkeypatch,
            capsys,
            accel=None,
            cpu={
                "backend": "cpu",
                "xla_tput": 9.0,
                "checksum": 7,
                "stages": {"median7": {"ms_per_batch": 1.0}},
            },
            probe_ok=False,
        )
        assert out["backend"] == "cpu"
        assert out["value"] == 9.0
        assert out["vs_baseline"] == 1.0
        assert "error" in out
        # the fallback record carries the stage breakdown for diagnosability
        assert out["stages"] == {"median7": {"ms_per_batch": 1.0}}

    def test_everything_lost_still_emits_json(self, monkeypatch, capsys):
        out, _ = self._run_main(monkeypatch, capsys, accel=None, cpu=None,
                                probe_ok=False)
        assert out["metric"] == "slices_per_sec_per_chip"
        assert out["backend"] == "none"
        assert out["value"] == 0.0
        assert "error" in out

    def test_cpu_baseline_lost_reports_raw_value(self, monkeypatch, capsys):
        out, _ = self._run_main(
            monkeypatch,
            capsys,
            accel={"backend": "tpu", "xla_tput": 100.0, "checksum": 7},
            cpu=None,
        )
        assert out["value"] == 100.0
        assert out["vs_baseline"] == 1.0
        assert "error" in out

    def test_partial_without_headline_discarded(self, monkeypatch, capsys):
        # sections file had only {"backend": ...} when the worker was killed
        out, calls = self._run_main(
            monkeypatch,
            capsys,
            accel={"backend": "tpu"},
            cpu={"backend": "cpu", "xla_tput": 9.0, "checksum": 7},
        )
        assert out["backend"] == "cpu"
        assert out["value"] == 9.0

    def test_wedge_banks_cpu_first_then_vigil_recovers_accel(
        self, monkeypatch, capsys
    ):
        # the round-3 flow: probe round fails -> CPU baseline (full sweep)
        # runs immediately -> the vigil later catches the tunnel -> the accel
        # record still wins the round, with vs_baseline taken from the CPU
        # sweep entry at the accel-winning batch (same-program ratio)
        calls = {}

        def fake_measure(label, worker_args, env_overrides, timeout_s):
            calls[label] = list(worker_args)
            if "accel" in label:
                return {
                    "backend": "tpu",
                    "xla_tput": 1000.0,
                    "xla_batch": 128,
                    "checksum": 7,
                }
            return {
                "backend": "cpu",
                "xla_tput": 10.0,
                "xla_batch": 32,
                "checksum": 7,
                "volume": {"ms_per_volume": 9.9},
                "xla_by_batch": {"32": 10.0, "128": 8.0},
            }

        monkeypatch.setattr(bench, "_probe_until_healthy", lambda *a: False)
        monkeypatch.setattr(bench, "_accel_vigil", lambda *a: True)
        monkeypatch.setattr(bench, "_run_measurement", fake_measure)
        bench.main()
        out = _emitted(capsys)
        # CPU ran before the vigil, sweeping every accel batch with stages
        cpu_args = calls["cpu baseline"]
        assert cpu_args[cpu_args.index("--batches") + 1] == ",".join(
            str(b) for b in bench.ACCEL_BATCH_SWEEP
        )
        assert "--stages" in cpu_args
        # a wedged round's driver record still carries the 3D leg
        assert "--volume" in cpu_args
        # the late accel record wins, ratioed against the batch-128 CPU entry
        assert out["backend"] == "tpu"
        # sections only the CPU baseline measured ride along under a
        # distinct key (never masquerading as accelerator numbers)
        assert out["cpu_diagnostics"]["volume"] == {"ms_per_volume": 9.9}
        assert out["value"] == 1000.0
        assert out["cpu_baseline_tput"] == 8.0
        assert out["vs_baseline"] == pytest.approx(125.0)
        assert "error" not in out
        # the SIGKILL-proof on-disk copy tracked the run (gitignored)
        banked = json.loads(self.partial_path.read_text())
        assert banked["value"] == out["value"]

    def test_wedge_vigil_exhausted_emits_cpu_fallback(self, monkeypatch, capsys):
        out, calls = self._run_main(
            monkeypatch,
            capsys,
            accel={"backend": "tpu", "xla_tput": 999.0, "checksum": 7},
            cpu={"backend": "cpu", "xla_tput": 9.0, "checksum": 7},
            probe_ok=False,
            vigil_ok=False,
        )
        # the accel stub was never consulted: vigil never recovered
        assert out["backend"] == "cpu"
        assert out["value"] == 9.0
        assert "accel measurement" not in calls

    def test_emitted_record_carries_sha_and_points_at_diagnostics(
        self, monkeypatch, capsys
    ):
        # VERDICT r4 item 1: probe history (ps/TCP snapshots, unbounded)
        # lives ONLY in the banked file; the stdout line stays small and
        # points at it via "detail"
        out, _ = self._run_main(
            monkeypatch,
            capsys,
            accel={"backend": "tpu", "xla_tput": 100.0, "checksum": 7},
            cpu={"backend": "cpu", "xla_tput": 8.0, "checksum": 7},
        )
        assert out["git_sha"]  # "unknown" only if git itself is unavailable
        assert "probe_history" not in out
        assert out["detail"] == str(self.partial_path)
        assert out["elapsed_s"] >= 0
        banked = json.loads(self.partial_path.read_text())
        assert isinstance(banked["probe_history"], list)

    def test_final_line_capped_and_newline_framed(self, monkeypatch, capsys):
        # the final stdout line must stay under the PIPE_BUF atomicity cap
        # whatever diagnostics accumulate, and must START on a fresh line so
        # a dangling partial stderr line in a merged stream cannot glue to it
        accel = {
            "backend": "tpu", "xla_tput": 100.0, "checksum": 7,
            # a pathologically large optional section: must be shed from the
            # line (but kept in the banked file)
            "stages": {f"stage_{i}": {"ms": i, "note": "x" * 64}
                       for i in range(200)},
        }
        monkeypatch.setattr(bench, "_probe_until_healthy", lambda *a: True)
        monkeypatch.setattr(bench, "_accel_vigil", lambda *a: False)
        monkeypatch.setattr(
            bench, "_run_measurement",
            lambda label, *a: accel if "accel" in label
            else {"backend": "cpu", "xla_tput": 8.0, "checksum": 7},
        )
        bench.main()
        raw = capsys.readouterr().out
        lines = raw.splitlines()
        assert lines[-1].strip(), "final line must be the record"
        assert lines[-2] == "", "record must be preceded by a framing newline"
        assert len(lines[-1]) <= bench._FINAL_LINE_CAP
        out = json.loads(lines[-1])
        assert out["value"] == 100.0
        assert "stages" not in out  # shed from the line...
        banked = json.loads(self.partial_path.read_text())
        assert len(banked["stages"]) == 200  # ...but intact on disk

    def test_accel_vigil_tcp_open_triggers_early_probe(self, monkeypatch):
        # the vigil's cheap TCP tier must fire the expensive jax probe
        # within seconds of the relay port opening, instead of waiting for
        # the 3-minute schedule — simulated clock, no real sleeping
        now = [0.0]
        monkeypatch.setattr(bench.time, "monotonic", lambda: now[0])
        monkeypatch.setattr(
            bench.time, "sleep", lambda s: now.__setitem__(0, now[0] + s)
        )
        probes = []
        monkeypatch.setattr(
            bench, "_tunnel_tcp_probe",
            lambda: {"p": "open" if now[0] > 100 else "closed(111)"},
        )

        def probe_once(env, label, t0, timeout_s=bench.PROBE_TIMEOUT_S):
            probes.append(now[0])
            # a probe against a sick tunnel costs its (possibly backed-off)
            # timeout; record rc=None so the vigil's halving logic engages
            now[0] += timeout_s
            bench._PROBE_HISTORY.append({"rc": None})
            return now[0] > 250  # recovers on the third attempt

        monkeypatch.setattr(bench, "_probe_once", probe_once)
        assert bench._accel_vigil({}, 0.0, 2000.0)
        assert probes[0] == 0.0  # probe-on-entry preserved
        # the relay opened at t=100; the reaction landed well inside the
        # old 180s spacing (at ~110s, one 20s TCP tick + rate limit)
        assert any(100 < t < 180 for t in probes[1:]), probes

    def test_probe_once_records_diagnostics(self, monkeypatch):
        # a timed-out probe (rc None) must leave stderr tail + claim-holder
        # snapshot in the history — the round-2 record was undiagnosable
        monkeypatch.setattr(
            bench, "_spawn", lambda *a: (None, "", "tunnel stuck somewhere")
        )
        monkeypatch.setattr(bench, "_claim_holder_snapshot", lambda: "pid 42 jax")
        bench._PROBE_HISTORY.clear()
        assert not bench._probe_once({}, "t", 0.0)
        entry = bench._PROBE_HISTORY[-1]
        assert entry["rc"] is None
        assert entry["stderr_tail"] == "tunnel stuck somewhere"
        assert entry["claim_holders"] == "pid 42 jax"

    def test_wedged_tunnel_exits_inside_budget(self, monkeypatch, capsys):
        # The round-3 regression (VERDICT r3 weak item 1): BENCH_r03 was
        # rc=124/parsed:null because the vigil outlived the driver's 1800 s
        # kill. Simulated clock + fully wedged tunnel: every probe and every
        # accel-facing child hangs to its timeout, only the CPU baseline
        # answers — the orchestrator must still emit inside its wall budget.
        now = [0.0]
        monkeypatch.setattr(bench.time, "monotonic", lambda: now[0])
        monkeypatch.setattr(
            bench.time, "sleep", lambda s: now.__setitem__(0, now[0] + s)
        )
        # the SIGALRM backstop is meaningless under a simulated clock, and
        # _git_sha's real subprocesses would burn fake time (Popen's wait
        # polls via the patched time.sleep)
        monkeypatch.setattr(bench.signal, "alarm", lambda s: 0)
        monkeypatch.setattr(bench, "_git_sha", lambda: "test")
        budget = bench.VIGIL_BUDGET_DEFAULT_S
        assert budget <= 1500.0  # the driver kills at 1800 s; keep slack

        def fake_spawn(label, args, env, timeout_s):
            if "--platform" in args:  # the CPU worker: tunnel-independent
                now[0] += 60
                rec = {"backend": "cpu", "xla_tput": 9.0, "checksum": 7}
                return 0, bench._SENTINEL + json.dumps(rec) + "\n", ""
            now[0] += timeout_s  # probe/accel: hangs until killed
            return None, "", "wedged"

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        monkeypatch.setattr(bench, "_tunnel_tcp_probe", lambda: {})
        monkeypatch.setattr(bench, "_claim_holder_snapshot", lambda: "")
        bench.main()
        out = _emitted(capsys)
        assert now[0] <= budget, f"orchestrator ran {now[0]}s > budget {budget}s"
        assert out["backend"] == "cpu"
        assert out["value"] == 9.0
        assert out["elapsed_s"] <= budget

    def test_late_vigil_recovery_sheds_to_reduced_attempt(
        self, monkeypatch, capsys
    ):
        # a tunnel that recovers with only ~5 minutes of budget left must
        # get a REDUCED attempt (no sweep/stages/pallas), not the full
        # program whose timeout would overrun the driver kill
        now = [0.0]
        monkeypatch.setattr(bench.time, "monotonic", lambda: now[0])
        monkeypatch.setattr(
            bench.time, "sleep", lambda s: now.__setitem__(0, now[0] + s)
        )
        monkeypatch.setattr(bench.signal, "alarm", lambda s: 0)
        monkeypatch.setattr(bench, "_git_sha", lambda: "test")
        deadline = bench.VIGIL_BUDGET_DEFAULT_S
        recover_at = deadline - 480.0
        calls = {}

        def fake_spawn(label, args, env, timeout_s):
            if "--probe" in args:
                if now[0] >= recover_at:
                    now[0] += 5
                    rec = {"backend": "tpu"}
                    return 0, bench._SENTINEL + json.dumps(rec) + "\n", ""
                now[0] += timeout_s
                return None, "", "wedged"
            calls[label] = (list(args), timeout_s)
            now[0] += 30
            if "--platform" in args:
                rec = {"backend": "cpu", "xla_tput": 9.0, "checksum": 7}
            else:
                rec = {"backend": "tpu", "xla_tput": 500.0, "checksum": 7}
            return 0, bench._SENTINEL + json.dumps(rec) + "\n", ""

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        monkeypatch.setattr(bench, "_tunnel_tcp_probe", lambda: {})
        monkeypatch.setattr(bench, "_claim_holder_snapshot", lambda: "")
        bench.main()
        out = _emitted(capsys)
        assert now[0] <= deadline
        assert out["backend"] == "tpu" and out["value"] == 500.0
        accel_args, accel_timeout = next(
            v for k, v in calls.items() if "accel" in k
        )
        assert "--stages" not in accel_args  # sweep/stages shed first
        assert "--pallas" not in accel_args
        # capped to the true remaining budget, below the full-program tier
        assert accel_timeout < bench.MIN_ACCEL_FULL_S

    def test_measure_accel_vigil_path_reserves_no_cpu(self, monkeypatch):
        # the vigil path runs no CPU work after the attempt: reserving
        # CPU_RESERVE_S there double-counted the already-banked baseline and
        # skipped late recoveries that genuinely fit a reduced attempt
        now = [0.0]
        monkeypatch.setattr(bench.time, "monotonic", lambda: now[0])
        calls = {}

        def fake_run(label, args, env, timeout_s):
            calls["args"], calls["timeout"] = list(args), timeout_s
            return {"backend": "tpu", "xla_tput": 1.0}

        monkeypatch.setattr(bench, "_run_measurement", fake_run)
        # 280 s left (the vigil floor region): banked baseline -> reduced
        # attempt with timeout 280-45=235; the old double reserve skipped it
        res = bench._measure_accel(deadline=280.0, cpu_banked=True)
        assert res is not None
        assert calls["timeout"] == pytest.approx(235.0)
        assert "--stages" not in calls["args"]
        # same remaining on the initial path: the CPU-baseline reserve is
        # sacrificed (a TPU headline with vs_baseline unknown beats a
        # CPU-only record), keeping a minimal 60 s baseline slot viable
        # beside the attempt since 235-60 still fits the reduced floor
        res2 = bench._measure_accel(deadline=280.0, cpu_banked=False)
        assert res2 is not None
        assert calls["timeout"] == pytest.approx(175.0)
        # below the reduced floor even without the CPU reserve: skip
        assert bench._measure_accel(deadline=150.0, cpu_banked=False) is None

    def test_merged_sections_recovered_from_file(self, monkeypatch, tmp_path):
        # _run_measurement must recover sections when the worker is killed
        # (rc None) — simulate via a stub _spawn that writes the file then
        # reports a timeout
        def fake_spawn(label, args, env, timeout_s):
            out_path = args[args.index("--out") + 1]
            with open(out_path, "a") as f:
                f.write(json.dumps({"backend": "tpu"}) + "\n")
                f.write(json.dumps({"xla_tput": 42.0, "checksum": 3}) + "\n")
            return None, ""  # timeout

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        res = bench._run_measurement("x", [], {}, 1)
        assert res == {"backend": "tpu", "xla_tput": 42.0, "checksum": 3}


@pytest.mark.slow
class TestExitPaths:
    """Real-subprocess exit-path guarantees (VERDICT r3 item 1): whatever
    the environment does, ``python bench.py`` exits rc 0 with a parseable
    JSON record as the FINAL stdout line — the driver parses exactly that.
    The accelerator env is scrubbed so these can never dial (or wedge) a
    real tunnel."""

    _SCRUB = {
        # a junk platform makes every probe fail fast without any jax
        # backend ever touching real hardware
        "JAX_PLATFORMS": "nonexistent_backend",
        "PALLAS_AXON_POOL_IPS": "",
    }

    def _popen(self, tmp_path, budget):
        env = os.environ.copy()
        env.update(self._SCRUB)
        env["NM03_BENCH_PARTIAL_PATH"] = str(tmp_path / "partial.json")
        env[bench.VIGIL_BUDGET_ENV] = str(budget)
        return subprocess.Popen(
            [sys.executable, str(_BENCH_PATH)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )

    @staticmethod
    def _final_record(out):
        lines = [l for l in out.splitlines() if l.strip()]
        assert lines, "no stdout at all"
        return json.loads(lines[-1])

    def test_exhausted_budget_emits_immediately_rc0(self, tmp_path):
        # budget too small for any phase: probes, baseline and vigil are all
        # skipped and the orchestrator emits a well-formed empty record fast
        proc = self._popen(tmp_path, budget=1)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0
        rec = self._final_record(out)
        assert rec["metric"] == "slices_per_sec_per_chip"
        assert rec["backend"] == "none"
        assert rec["elapsed_s"] < 30

    def test_sigterm_emits_parseable_final_line_rc0(self, tmp_path):
        # an external kill mid-run (the driver's timeout sends SIGTERM
        # first) must produce rc 0 + best-so-far JSON as the last line
        proc = self._popen(tmp_path, budget=600)
        time.sleep(10)  # inside probe round / backoff by now
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0
        rec = self._final_record(out)
        assert rec["metric"] == "slices_per_sec_per_chip"
        assert rec["terminated"].startswith("signal")

    def test_driver_pipe_merged_stderr_last_line_parses(self, tmp_path):
        # VERDICT r4 item 1, the exact failure mode: the driver runs bench
        # as `... 2>&1 | tail -100` and json-parses the LAST line. Recreate
        # that pipeline with hostile stderr: a dangling partial line written
        # just before bench starts, plus concurrent chatter racing the
        # merged stream. The record must still be the last line, parseable,
        # and under the PIPE_BUF atomicity cap.
        env = os.environ.copy()
        env.update(self._SCRUB)
        env["NM03_BENCH_PARTIAL_PATH"] = str(tmp_path / "partial.json")
        env[bench.VIGIL_BUDGET_ENV] = "1"
        # a burst of stderr chatter then a DANGLING partial line immediately
        # before bench starts; bench's own stderr logging (probe attempts,
        # phase skips) supplies the concurrent chatter racing the merged
        # stream while it runs
        script = (
            "{ for i in $(seq 1 50); do printf 'chatter %d\\n' \"$i\" >&2; done; "
            "printf 'dangling-partial-stderr-line' >&2; "
            f"{sys.executable} {_BENCH_PATH}; }} 2>&1 | tail -100"
        )
        out = subprocess.run(
            ["bash", "-c", script], capture_output=True, text=True,
            env=env, timeout=120,
        )
        lines = [l for l in out.stdout.splitlines() if l.strip()]
        assert lines, "no output through the driver pipe"
        assert len(lines[-1]) <= 4096, "final line exceeds PIPE_BUF atomicity"
        rec = json.loads(lines[-1])
        assert rec["metric"] == "slices_per_sec_per_chip"
        assert "probe_history" not in rec
        banked = json.loads((tmp_path / "partial.json").read_text())
        assert "probe_history" in banked


class TestBatchScalingNote:
    def test_note_emitted_for_regressing_larger_batch(self):
        # the r05 record: 116.09 @128 vs 111.61 @256 with no explanation
        note = bench._batch_scaling_note(
            {"32": 115.67, "128": 116.09, "256": 111.61}, 128, canvas=256
        )
        assert note is not None
        assert "batch 256" in note and "67 MB" in note
        assert "cache footprint" in note

    def test_no_note_when_flat_or_best_is_largest(self):
        assert bench._batch_scaling_note(
            {"32": 100.0, "128": 101.0, "256": 102.0}, 256, canvas=256
        ) is None
        # within 3%: measurement noise, not worth a paragraph
        assert bench._batch_scaling_note(
            {"32": 100.0, "128": 100.0, "256": 99.0}, 128, canvas=256
        ) is None
        assert bench._batch_scaling_note({}, None, canvas=256) is None

    @pytest.mark.slow
    def test_worker_emits_note_on_sweep(self, monkeypatch, capsys):
        # tiny sweep on the CPU backend: when a larger batch measures
        # slower, the sections carry batch_note (can't force the slowdown
        # deterministically, so stub the measurement)
        tputs = {2: 50.0, 4: 40.0}
        monkeypatch.setattr(bench, "CANVAS", 64)
        monkeypatch.setattr(
            bench, "_bench_on",
            lambda dev, px, dm, reps, use_pallas=False: (tputs[px.shape[0]], 7),
        )
        bench.worker("cpu", reps=1, want_pallas=False, want_stages=False,
                     out_path=None, batches=(2, 4))
        res = _emitted(capsys)
        assert "batch 4" in res["batch_note"]
        assert res["xla_batch"] == 2


class TestVigilProbeBackoff:
    def test_consecutive_timeouts_halve_probe_work(self, monkeypatch):
        # r05: vigil probe 4 burned a full 90 s with the budget nearly
        # spent. Consecutive timeouts must shrink the probe timeout toward
        # the floor; a fast-error probe resets it.
        now = [0.0]
        monkeypatch.setattr(bench.time, "monotonic", lambda: now[0])
        monkeypatch.setattr(
            bench.time, "sleep", lambda s: now.__setitem__(0, now[0] + s)
        )
        monkeypatch.setattr(bench, "_tunnel_tcp_probe", lambda: {})
        timeouts = []

        def probe_once(env, label, t0, timeout_s=bench.PROBE_TIMEOUT_S):
            timeouts.append(timeout_s)
            now[0] += timeout_s
            bench._PROBE_HISTORY.append({"rc": None})  # timeout
            return False

        monkeypatch.setattr(bench, "_probe_once", probe_once)
        bench._PROBE_HISTORY.clear()
        assert not bench._accel_vigil({}, 0.0, 1500.0)
        assert timeouts[0] == bench.PROBE_TIMEOUT_S
        assert timeouts[1] == bench.PROBE_TIMEOUT_S // 2
        # monotone non-increasing down to the floor, never below it
        assert all(b <= a for a, b in zip(timeouts, timeouts[1:]))
        assert min(timeouts) == bench.VIGIL_PROBE_MIN_TIMEOUT_S
        # cheap probes fire on a proportionally tighter cadence, so the
        # vigil gets MORE chances at a late recovery for the same wall
        assert len(timeouts) >= 8

    def test_vigil_reserves_the_zshard_slot(self, monkeypatch, capsys):
        # a fully wedged tunnel must still leave room for the zshard
        # section (r05 skipped it entirely): the vigil deadline passed by
        # main() is ZSHARD_RESERVE_S short of the wall budget
        seen = {}

        def fake_vigil(env, t0, deadline):
            seen["deadline"] = deadline
            return False

        monkeypatch.setattr(bench, "_PARTIAL_PATH", "/tmp/bench_partial_t2.json")
        monkeypatch.setattr(bench, "_probe_until_healthy", lambda *a: False)
        monkeypatch.setattr(bench, "_accel_vigil", fake_vigil)
        monkeypatch.setattr(
            bench, "_run_measurement",
            lambda *a: {"backend": "cpu", "xla_tput": 9.0, "checksum": 7},
        )
        zshard_deadlines = {}

        def fake_zshard(deadline):
            zshard_deadlines["deadline"] = deadline
            return {"ms": {"1": 5.0}}

        monkeypatch.setattr(bench, "_measure_zshard", fake_zshard)
        t0 = bench.time.monotonic()
        bench.main()
        out = _emitted(capsys)
        assert out["zshard_scaling"] == {"ms": {"1": 5.0}}
        # vigil got ZSHARD_RESERVE_S less than the zshard section
        assert (
            zshard_deadlines["deadline"] - seen["deadline"]
            == pytest.approx(bench.ZSHARD_RESERVE_S, abs=1.0)
        )


class TestStageTableExtras:
    @pytest.mark.slow
    def test_stage_table_carries_comparators_and_deltas(self, monkeypatch):
        # ISSUE 2: the stage table must make the median/render rebuild
        # attributable — comparator counts and fast-vs-baseline timings
        monkeypatch.setattr(bench, "BATCH", 4)
        monkeypatch.setattr(bench, "STAGE_SMALL_BATCH", 2)
        monkeypatch.setattr(bench, "CANVAS", 64)
        import jax

        prof = bench._stage_times(jax.devices("cpu")[0], reps=2)
        med = prof["stages"]["median7"]
        comp = med["comparators"]
        assert comp["merge_minmax_pruned"] < comp["merge_minmax_full"]
        assert (
            comp["merge_minmax_pruned_shared"] <= comp["merge_minmax_pruned"]
        )
        assert med["merge_baseline_ms_per_batch"] > 0
        assert med["pruned_vs_merge_speedup"] > 0
        rend = prof["stages"]["render"]
        assert rend["unfused_ms_per_batch"] > 0
        assert rend["fused_vs_unfused_speedup"] > 0

    def test_path_metrics_reach_the_snapshot(self, monkeypatch, tmp_path):
        # --metrics-out must record which median/render path ran plus the
        # comparator counts (ISSUE 2 satellite)
        record = {
            "backend": "cpu",
            "xla_tput": 10.0,
            "winning_path": "xla",
            "stages": {
                "median7": {
                    "comparators": {
                        "merge_minmax_full": 566,
                        "merge_minmax_pruned": 346,
                        "merge_minmax_pruned_shared": 262,
                        "presort_minmax": 32,
                    }
                },
                "render": {"fused_vs_unfused_speedup": 1.4},
            },
        }
        from nm03_capstone_project_tpu.obs import RunContext

        ctx = RunContext.create("bench")
        monkeypatch.setattr(bench, "_OBS_CTX", ctx)
        bench._record_path_metrics(record)
        snap = ctx.metrics_snapshot()
        series = {
            (m["name"], tuple(sorted(m.get("labels", {}).items()))): m["value"]
            for m in snap["metrics"]
        }
        assert (
            "nm03_median_comparator_minmax_ops",
            (("variant", "merge_minmax_pruned"),),
        ) in series
        info = [
            m for m in snap["metrics"] if m["name"] == "nm03_pipeline_path_info"
        ]
        assert info and info[0]["labels"]["render"] == "fused"
        assert info[0]["labels"]["winning_path"] == "xla"


class TestCheckBenchRegression:
    """scripts/check_bench_regression.py smoke tests (ISSUE 2 satellite)."""

    @staticmethod
    def _script():
        import importlib.util as iu

        path = pathlib.Path(__file__).parents[1] / "scripts" / "check_bench_regression.py"
        spec = iu.spec_from_file_location("cbr", path)
        mod = iu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @staticmethod
    def _record(ms, backend="cpu"):
        return {
            "backend": backend,
            "stages": {k: {"ms_per_batch": v} for k, v in ms.items()},
        }

    def test_regression_exits_nonzero(self, tmp_path, capsys):
        cbr = self._script()
        base = tmp_path / "BASELINE.json"
        base.write_text(json.dumps({
            "stage_baseline": {
                "backend": "cpu",
                "ms_per_batch": {"median7": 100.0, "render": 50.0},
            }
        }))
        res = tmp_path / "r.json"
        res.write_text(json.dumps(self._record({"median7": 120.0, "render": 49.0})))
        rc = cbr.main([str(res), "--baseline", str(base)])
        assert rc == 1
        assert "REGRESSION median7" in capsys.readouterr().out

    def test_within_threshold_and_improvements_pass(self, tmp_path):
        cbr = self._script()
        base = tmp_path / "BASELINE.json"
        base.write_text(json.dumps({
            "stage_baseline": {
                "backend": "cpu",
                "ms_per_batch": {"median7": 100.0, "render": 50.0},
            }
        }))
        res = tmp_path / "r.json"
        res.write_text(json.dumps(self._record({"median7": 105.0, "render": 20.0})))
        assert cbr.main([str(res), "--baseline", str(base)]) == 0

    def test_cross_backend_skips(self, tmp_path, capsys):
        cbr = self._script()
        base = tmp_path / "BASELINE.json"
        base.write_text(json.dumps({
            "stage_baseline": {
                "backend": "cpu",
                "ms_per_batch": {"median7": 100.0},
            }
        }))
        res = tmp_path / "r.json"
        res.write_text(json.dumps(self._record({"median7": 900.0}, backend="tpu")))
        assert cbr.main([str(res), "--baseline", str(base)]) == 0
        assert "backend mismatch" in capsys.readouterr().out

    def test_driver_capture_shape_and_update(self, tmp_path):
        # accepts the BENCH_r*.json {"parsed": {...}} wrapper, and --update
        # seeds the baseline section
        cbr = self._script()
        base = tmp_path / "BASELINE.json"
        base.write_text(json.dumps({"metric": "x"}))
        res = tmp_path / "r.json"
        res.write_text(json.dumps({
            "parsed": self._record({"median7": 80.0, "render": 40.0})
        }))
        assert cbr.main([str(res), "--baseline", str(base), "--update"]) == 0
        doc = json.loads(base.read_text())
        assert doc["stage_baseline"]["ms_per_batch"]["median7"] == 80.0
        # and the seeded baseline then gates
        worse = tmp_path / "w.json"
        worse.write_text(json.dumps(self._record({"median7": 100.0})))
        assert cbr.main([str(worse), "--baseline", str(base)]) == 1

    def test_repo_baseline_is_seeded_and_consistent(self):
        # the committed BASELINE.json carries the r05 CPU stage floor the
        # gate diffs against
        repo = pathlib.Path(__file__).parents[1]
        doc = json.loads((repo / "BASELINE.json").read_text())
        section = doc["stage_baseline"]
        assert section["backend"] == "cpu"
        assert section["ms_per_batch"]["median7"] == pytest.approx(211.127)
        cbr = self._script()
        backend, stages = cbr.extract_stages(
            json.loads((repo / "BENCH_r05.json").read_text())
        )
        assert backend == "cpu"
        assert stages == section["ms_per_batch"]


def test_make_batch_radius_distribution_is_batch_invariant():
    """VERDICT r4 weak #5: the sweep generator must give every batch size
    the same lesion-radius distribution, or xla_by_batch measures lesion
    scaling (the batched grow fixpoint runs to the LARGEST lesion), not
    batch scaling — the round-4 'inversion'."""
    import inspect

    src = inspect.getsource(bench._make_batch)
    assert "% 32" in src, "radius must cycle, not grow with the raw index"
    px32, _ = bench._make_batch(32)
    px256, _ = bench._make_batch(256)
    # the headline batch is bit-identical to prior rounds' (seeds 0-31,
    # same radii), so records stay comparable across the fix
    np.testing.assert_array_equal(px256[:32], px32)
