"""Pallas median kernel vs the portable XLA oracle (interpret mode on CPU).

The Pallas TPU kernel must be bit-identical to
:func:`ops.median.vector_median_filter` — same rank statistics, same
clamp-to-edge boundaries — so the whole correctness suite transfers to the
TPU path by this equivalence.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from nm03_capstone_project_tpu.data.synthetic import phantom_slice
from nm03_capstone_project_tpu.ops.median import vector_median_filter
from nm03_capstone_project_tpu.ops.pallas_median import (
    _pick_tile,
    median_filter,
    vector_median_filter_pallas,
)


class TestPickTile:
    def test_full_band_even_for_prime_heights(self):
        # the old divisor search degenerated to tile=1 (a per-row grid) on
        # prime h; the wrapper now pads rows instead (VERDICT r3 item 3).
        # Bands are sublane-aligned (multiple of 8) unless h itself is tiny.
        for h in (256, 97, 127, 64):
            assert _pick_tile(h) == 64
        assert _pick_tile(30) == 24  # rounded down to the 8-row sublane tile
        assert _pick_tile(7) == 7  # block rows == array rows is legal

    def test_wide_canvas_shrinks_band_for_vmem(self):
        # the 1024^2 OOM regression: the band must shrink as w grows so the
        # kernel's scoped VMEM stack stays inside the 16 MB budget
        assert _pick_tile(1024, 1024, 3) < 64
        assert _pick_tile(2048, 2048, 3) >= 8
        assert _pick_tile(1024, 1024, 3) % 8 == 0

    def test_unfittable_shapes_signal_fallback(self):
        # short-but-very-wide canvases (and big windows/dtypes) can't fit
        # even the minimum band: the wrapper must take the XLA path, not OOM
        assert _pick_tile(8, 20000, 3) is None
        assert _pick_tile(4, 100000, 3) is None
        # the budget scales with window size and element width
        assert (_pick_tile(1024, 1024, 4) or 0) <= _pick_tile(1024, 1024, 3)
        assert (_pick_tile(1024, 1024, 3, itemsize=8) or 0) <= _pick_tile(
            1024, 1024, 3, itemsize=4
        )

    def test_fallback_path_still_bit_exact(self, rng):
        # a shape _pick_tile refuses must silently produce the XLA result
        x = rng.random((6, 20000)).astype(np.float32)
        got = np.asarray(
            vector_median_filter_pallas(jnp.asarray(x), 7, interpret=True)
        )
        want = np.asarray(vector_median_filter(jnp.asarray(x), 7))
        np.testing.assert_array_equal(got, want)

    def test_prime_height_bit_exact(self, rng):
        # the row padding must not leak into the kept output rows
        x = rng.random((97, 61)).astype(np.float32)
        got = np.asarray(
            vector_median_filter_pallas(jnp.asarray(x), 7, interpret=True)
        )
        want = np.asarray(vector_median_filter(jnp.asarray(x), 7))
        np.testing.assert_array_equal(got, want)


class TestPallasMedianInterpret:
    @pytest.mark.parametrize("size", [3, 5, 7])
    def test_matches_xla_oracle_random(self, rng, size):
        x = rng.random((32, 48)).astype(np.float32)
        got = np.asarray(
            vector_median_filter_pallas(jnp.asarray(x), size, interpret=True)
        )
        want = np.asarray(vector_median_filter(jnp.asarray(x), size))
        np.testing.assert_array_equal(got, want)

    def test_matches_on_phantom(self):
        x = phantom_slice(64, 64, seed=5)
        got = np.asarray(
            vector_median_filter_pallas(jnp.asarray(x), 7, interpret=True)
        )
        want = np.asarray(vector_median_filter(jnp.asarray(x), 7))
        np.testing.assert_array_equal(got, want)

    def test_batched_input(self, rng):
        x = rng.random((3, 16, 24)).astype(np.float32)
        got = np.asarray(
            vector_median_filter_pallas(jnp.asarray(x), 3, interpret=True)
        )
        want = np.asarray(vector_median_filter(jnp.asarray(x), 3))
        np.testing.assert_array_equal(got, want)

    def test_ties_resolved_identically(self, rng):
        # heavy ties: quantized values exercise the (value, index) tie-break
        x = (rng.integers(0, 4, (24, 24))).astype(np.float32)
        got = np.asarray(
            vector_median_filter_pallas(jnp.asarray(x), 7, interpret=True)
        )
        want = np.asarray(vector_median_filter(jnp.asarray(x), 7))
        np.testing.assert_array_equal(got, want)

    def test_even_size_raises(self):
        with pytest.raises(ValueError):
            vector_median_filter_pallas(jnp.zeros((8, 8)), 4, interpret=True)


class TestFusedPreprocess:
    """The fused normalize->clip->median->sharpen band kernel vs the
    unfused XLA composition (interpret mode on CPU).

    Contract (module docstring): windowing/halo semantics exact, scalar
    arithmetic within a few ulp of the JITTED unfused composition — the
    two are separately compiled programs and LLVM's fma contraction of
    ``a*b+c`` is fusion-shape-dependent, so strict bit equality is
    unobtainable for the arithmetic stages (the median band kernel, pure
    min/max, stays bit-identical above). The reference is jitted because
    that is what the pipeline runs — measured, the EAGER evaluation of
    the same unfused code differs from its own jit by MORE than the
    kernel differs from the jit, so the kernel sits inside the baseline's
    own compilation variance.
    """

    @staticmethod
    def _want(x):
        import functools

        import jax

        from nm03_capstone_project_tpu.ops.pallas_median import (
            _fused_preprocess_xla,
        )

        ref = jax.jit(
            functools.partial(
                _fused_preprocess_xla,
                norm_low=0.5,
                norm_high=2.5,
                norm_min=0.0,
                norm_max=10000.0,
                clip_low=0.68,
                clip_high=4000.0,
                median_window=7,
                sharpen_gain=2.0,
                sharpen_sigma=0.5,
                sharpen_kernel=9,
            )
        )
        return np.asarray(ref(jnp.asarray(x)))

    @pytest.mark.parametrize(
        "shape",
        [(64, 64), (97, 61), (33, 47), (16, 40), (70, 33), (2, 40, 40)],
    )
    def test_within_ulp_bound_of_unfused(self, shape):
        # prime heights, non-tile-multiples and a batch axis: the halo /
        # band fixup arithmetic must hold everywhere, including the
        # canvas-boundary rows where the kernel replicates the median's
        # own edge rows instead of re-running the median on replicated
        # input (the two are NOT the same — see the kernel docstring).
        # Bound 8: the unsharp update's cancellation (center + gain *
        # (center - blur)) amplifies the 1-ulp fma variance of the blur;
        # measured <= 4 ulp across 90 random canvases, 8 leaves margin
        # while still catching any real halo/windowing bug (those miss by
        # whole median values, thousands of ulp). A local deterministic
        # rng: the session fixture's stream depends on test order, and a
        # data-dependent ulp bound must not flake with suite composition.
        from nm03_capstone_project_tpu.ops.pallas_median import (
            fused_preprocess_pallas,
        )

        rng = np.random.default_rng(sum(shape))
        x = (rng.random(shape) * 9000.0).astype(np.float32)
        got = np.asarray(fused_preprocess_pallas(jnp.asarray(x), interpret=True))
        np.testing.assert_array_max_ulp(got, self._want(x), maxulp=8)

    def test_on_phantom(self):
        from nm03_capstone_project_tpu.ops.pallas_median import (
            fused_preprocess_pallas,
        )

        x = phantom_slice(64, 64, seed=5) * 9000.0
        got = np.asarray(fused_preprocess_pallas(jnp.asarray(x), interpret=True))
        np.testing.assert_array_max_ulp(got, self._want(x), maxulp=8)

    def test_band_smaller_than_sharpen_halo_falls_back(self):
        # tile < rs (large sharpen kernel, tiny canvas): interior bands
        # would overhang the canvas beyond the two-candidate boundary
        # fixup's reach, so the wrapper must take the XLA composition —
        # caught in review: before the guard this silently broke the ulp
        # contract (measured 8e-3 absolute deviation on this exact case)
        import functools

        import jax

        from nm03_capstone_project_tpu.ops.pallas_median import (
            _fused_preprocess_xla,
            _pick_tile,
            fused_preprocess_pallas,
        )

        rng = np.random.default_rng(19)
        kw = dict(
            norm_low=0.5, norm_high=2.5, norm_min=0.0, norm_max=10000.0,
            clip_low=0.68, clip_high=4000.0, median_window=7,
            sharpen_gain=2.0, sharpen_sigma=5.0, sharpen_kernel=19,
        )
        x = (rng.random((12, 40)) * 9000.0).astype(np.float32)
        assert (_pick_tile(12, 40, 3 + 9) or 0) < 9  # the triggering regime
        got = np.asarray(fused_preprocess_pallas(jnp.asarray(x), interpret=True, **kw))
        want = np.asarray(
            jax.jit(functools.partial(_fused_preprocess_xla, **kw))(jnp.asarray(x))
        )
        np.testing.assert_array_max_ulp(got, want, maxulp=8)

    def test_unfittable_shape_falls_back_to_xla(self, rng):
        # a canvas _pick_tile refuses must take the XLA composition (then
        # equality is exact — same program)
        from nm03_capstone_project_tpu.ops.pallas_median import (
            fused_preprocess_pallas,
        )

        x = (rng.random((6, 20000)) * 9000.0).astype(np.float32)
        got = np.asarray(fused_preprocess_pallas(jnp.asarray(x), interpret=True))
        np.testing.assert_array_equal(got, self._want(x))

    def test_pipeline_preprocess_routes_fused_on_tpu(self, monkeypatch):
        # cfg.use_pallas + cfg.fuse_preprocess on a TPU backend must reach
        # the fused kernel (sentinel), and --no-preprocess-fuse must not
        import jax

        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.ops import pallas_median as pm
        from nm03_capstone_project_tpu.pipeline import slice_pipeline as sp

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        sentinel = jnp.zeros((8, 8), jnp.float32)
        called = []
        monkeypatch.setattr(
            pm,
            "fused_preprocess_pallas",
            lambda x, **kw: called.append(kw) or sentinel,
        )
        cfg = PipelineConfig(use_pallas=True)
        out = sp.preprocess(
            jnp.zeros((8, 8), jnp.float32), jnp.asarray([8, 8], jnp.int32), cfg
        )
        assert out is sentinel and len(called) == 1
        assert called[0]["median_window"] == cfg.median_window


class TestDispatch:
    def test_use_pallas_on_cpu_falls_back(self, rng):
        # on the CPU backend the dispatcher must route to the XLA path
        x = jnp.asarray(rng.random((16, 16)).astype(np.float32))
        got = np.asarray(median_filter(x, 7, use_pallas=True))
        want = np.asarray(vector_median_filter(x, 7))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow
    def test_pipeline_cfg_use_pallas_runs_on_cpu(self):
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        cfg = PipelineConfig(use_pallas=True, grow_block_iters=8, grow_max_iters=128)
        x = jnp.asarray(phantom_slice(64, 64, seed=6))
        out = process_slice(x, jnp.asarray([64, 64], jnp.int32), cfg)
        assert np.asarray(out["mask"]).sum() > 0

    def test_non_cpu_non_tpu_backend_takes_xla_path(self, rng, monkeypatch):
        # VERDICT r1 weak #5: gating on backend != 'cpu' would send a GPU
        # backend into pltpu lowering and crash; the guard must be a TPU
        # allowlist. Simulate a GPU backend and assert neither dispatcher
        # touches its Pallas kernel.
        import jax

        from nm03_capstone_project_tpu.ops import pallas_median as pm
        from nm03_capstone_project_tpu.ops import pallas_region_growing as pr
        from nm03_capstone_project_tpu.ops.region_growing import region_grow

        monkeypatch.setattr(jax, "default_backend", lambda: "gpu")

        def boom(*a, **k):
            raise AssertionError("pallas kernel dispatched on a GPU backend")

        monkeypatch.setattr(pm, "vector_median_filter_pallas", boom)
        monkeypatch.setattr(pr, "region_grow_pallas", boom)

        x = jnp.asarray(rng.random((16, 16)).astype(np.float32))
        got = np.asarray(pm.median_filter(x, 7, use_pallas=True))
        want = np.asarray(vector_median_filter(x, 7))
        np.testing.assert_array_equal(got, want)

        seeds = jnp.zeros((16, 16), jnp.uint8).at[8, 8].set(1)
        got_m = pr.grow_dispatch(
            x, seeds, 0.0, 1.0, block_iters=8, max_iters=32, use_pallas=True
        )[0]
        want_m = region_grow(x, seeds, 0.0, 1.0, block_iters=8, max_iters=32)[0]
        np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))

    def test_tpu_backend_takes_pallas_path(self, monkeypatch):
        import jax

        from nm03_capstone_project_tpu.ops import pallas_median as pm

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        sentinel = object()
        monkeypatch.setattr(
            pm, "vector_median_filter_pallas", lambda *a, **k: sentinel
        )
        assert pm.median_filter(jnp.zeros((8, 8)), 7, use_pallas=True) is sentinel
