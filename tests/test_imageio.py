"""Generic image + MetaImage IO (data.imageio).

Covers the reference's declared-but-uninstantiated importer/exporter surface
(FAST_directives.hpp:27-31): round-trips, MetaIO header conventions
(fastest-first DimSize, spacing order), compression, and malformed-input
rejection.
"""

import numpy as np
import pytest

from nm03_capstone_project_tpu.data.imageio import (
    read_image,
    read_metaimage,
    write_image,
    write_metaimage,
)


class TestGenericImage:
    def test_png_roundtrip_exact(self, tmp_path):
        img = np.arange(48, dtype=np.uint8).reshape(6, 8) * 5
        p = tmp_path / "a.png"
        write_image(img, p)
        back = read_image(p)
        assert back.dtype == np.float32
        np.testing.assert_array_equal(back, img.astype(np.float32))

    def test_jpeg_roundtrip_close(self, tmp_path):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 255, (32, 32), np.uint8)
        p = tmp_path / "a.jpg"
        write_image(img, p)
        back = read_image(p)
        assert np.abs(back - img).mean() < 20  # lossy but in the ballpark

    def test_rgb_reads_as_luminance(self, tmp_path):
        img = np.zeros((4, 4, 3), np.uint8)
        img[..., 0] = 255  # pure red
        p = tmp_path / "rgb.png"
        write_image(img, p)
        back = read_image(p)
        assert back.shape == (4, 4)
        assert 50 < back.mean() < 100  # ITU-R luma of red ~76

    def test_rejects_non_uint8(self, tmp_path):
        with pytest.raises(ValueError, match="uint8"):
            write_image(np.zeros((4, 4), np.float32), tmp_path / "x.png")


class TestMetaImage:
    @pytest.mark.parametrize("dtype", [np.uint8, np.int16, np.uint16, np.float32])
    def test_roundtrip_2d(self, tmp_path, dtype):
        rng = np.random.default_rng(1)
        arr = rng.integers(0, 100, (5, 7)).astype(dtype)
        write_metaimage(arr, tmp_path / "s.mhd", spacing=(2.0, 0.5))
        back, spacing = read_metaimage(tmp_path / "s.mhd")
        np.testing.assert_array_equal(back, arr)
        assert back.dtype == np.dtype(dtype)
        assert spacing == (2.0, 0.5)

    def test_roundtrip_3d_compressed(self, tmp_path):
        rng = np.random.default_rng(2)
        vol = rng.normal(size=(4, 6, 8)).astype(np.float32)
        write_metaimage(vol, tmp_path / "v.mhd", compressed=True)
        assert (tmp_path / "v.zraw").exists()
        back, spacing = read_metaimage(tmp_path / "v.mhd")
        np.testing.assert_array_equal(back, vol)
        assert spacing == (1.0, 1.0, 1.0)

    def test_dimsize_is_fastest_first(self, tmp_path):
        # MetaIO convention: DimSize lists x y z; our array axes are (z, y, x)
        write_metaimage(np.zeros((2, 3, 4), np.uint8), tmp_path / "d.mhd")
        header = (tmp_path / "d.mhd").read_text()
        assert "DimSize = 4 3 2" in header

    def test_rejects_size_mismatch(self, tmp_path):
        write_metaimage(np.zeros((4, 4), np.uint8), tmp_path / "m.mhd")
        raw = tmp_path / "m.raw"
        raw.write_bytes(raw.read_bytes()[:-1])
        with pytest.raises(ValueError, match="bytes"):
            read_metaimage(tmp_path / "m.mhd")

    def test_rejects_missing_field(self, tmp_path):
        (tmp_path / "bad.mhd").write_text("ObjectType = Image\nNDims = 2\n")
        with pytest.raises(ValueError, match="missing"):
            read_metaimage(tmp_path / "bad.mhd")

    def test_rejects_unsupported_dtype(self, tmp_path):
        with pytest.raises(ValueError, match="dtype"):
            write_metaimage(np.zeros((2, 2), np.complex64), tmp_path / "c.mhd")

    def test_rejects_4d(self, tmp_path):
        with pytest.raises(ValueError, match="2D/3D"):
            write_metaimage(np.zeros((2, 2, 2, 2), np.uint8), tmp_path / "q.mhd")

    def test_mask_export_volume_pipeline_shape(self, tmp_path):
        # the practical use: persist a segmentation volume for ITK-SNAP et al.
        mask = (np.random.default_rng(3).random((3, 16, 16)) > 0.7).astype(np.uint8)
        write_metaimage(mask, tmp_path / "mask", spacing=(5.0, 1.0, 1.0))
        back, spacing = read_metaimage(tmp_path / "mask.mhd")
        np.testing.assert_array_equal(back, mask)
        assert spacing == (5.0, 1.0, 1.0)


def test_metaimage_mutation_fuzz_rejects_cleanly(tmp_path):
    """Byte-corrupted .mhd headers must decode or raise ValueError — never
    UnicodeDecodeError / IsADirectoryError / zlib.error (all observed before
    the round-3 guards)."""
    rng = np.random.default_rng(5)
    vol = (rng.random((4, 8, 8)) * 100).astype(np.uint8)
    write_metaimage(vol, tmp_path / "v.mhd")
    src = (tmp_path / "v.mhd").read_bytes()
    for _ in range(80):
        raw = bytearray(src)
        for _ in range(rng.integers(1, 5)):
            mode = rng.integers(0, 3)
            if mode == 0 and len(raw):
                raw[rng.integers(0, len(raw))] = rng.integers(0, 256)
            elif mode == 1 and len(raw) > 10:
                raw = raw[: rng.integers(5, len(raw))]
            else:
                at = rng.integers(0, len(raw))
                raw[at:at] = bytes(rng.integers(0, 256, 6, dtype=np.uint8))
        (tmp_path / "m.mhd").write_bytes(bytes(raw))
        try:
            read_metaimage(tmp_path / "m.mhd")
        except ValueError:
            pass


def test_metaimage_corrupt_compressed_payload_rejects_cleanly(tmp_path):
    """A corrupt .zraw must raise ValueError, not zlib.error."""
    import pytest

    vol = (np.random.default_rng(1).random((4, 8, 8)) * 100).astype(np.uint8)
    write_metaimage(vol, tmp_path / "c.mhd", compressed=True)
    zraw = tmp_path / "c.zraw"
    data = bytearray(zraw.read_bytes())
    data[: min(8, len(data))] = b"\xff" * min(8, len(data))
    zraw.write_bytes(bytes(data))
    with pytest.raises(ValueError, match="corrupt compressed"):
        read_metaimage(tmp_path / "c.mhd")
