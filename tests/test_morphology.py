import numpy as np
import scipy.ndimage as ndi

from nm03_capstone_project_tpu.ops import dilate, erode


def random_mask(rng, shape=(32, 32), p=0.3):
    return (rng.random(shape) < p).astype(np.uint8)


def cross_struct():
    return ndi.generate_binary_structure(2, 1)


def box_struct():
    return np.ones((3, 3), bool)


def test_dilate_cross_matches_scipy(rng):
    m = random_mask(rng)
    out = np.asarray(dilate(m, 3, "cross"))
    expected = ndi.binary_dilation(m, structure=cross_struct()).astype(np.uint8)
    np.testing.assert_array_equal(out, expected)


def test_dilate_box_matches_scipy(rng):
    m = random_mask(rng)
    out = np.asarray(dilate(m, 3, "box"))
    expected = ndi.binary_dilation(m, structure=box_struct()).astype(np.uint8)
    np.testing.assert_array_equal(out, expected)


def test_erode_cross_matches_scipy(rng):
    m = random_mask(rng, p=0.7)
    out = np.asarray(erode(m, 3, "cross"))
    expected = ndi.binary_erosion(
        m, structure=cross_struct(), border_value=0
    ).astype(np.uint8)
    np.testing.assert_array_equal(out, expected)


def test_erode_erodes_border_foreground():
    m = np.ones((8, 8), np.uint8)
    out = np.asarray(erode(m, 3, "box"))
    assert out[0, 0] == 0 and out[4, 4] == 1


def test_morphology_preserves_bool_dtype():
    m = np.zeros((8, 8), bool)
    m[4, 4] = True
    out = dilate(m, 3, "cross")
    assert np.asarray(out).dtype == bool
    assert np.asarray(out).sum() == 5


def test_disk_size3_equals_box():
    # euclidean radius 1.5 includes diagonals
    m = np.zeros((9, 9), np.uint8)
    m[4, 4] = 1
    np.testing.assert_array_equal(
        np.asarray(dilate(m, 3, "disk")), np.asarray(dilate(m, 3, "box"))
    )


def test_batched_matches_loop(rng):
    ms = np.stack([random_mask(rng) for _ in range(4)])
    out = np.asarray(dilate(ms, 3, "cross"))
    for i in range(4):
        np.testing.assert_array_equal(out[i], np.asarray(dilate(ms[i], 3, "cross")))
