import jax
import jax.numpy as jnp
import numpy as np

from nm03_capstone_project_tpu.ops import (
    binary_threshold,
    cast_uint8,
    clip_intensity,
    normalize,
)


def test_normalize_reference_window(rng):
    """The reference window: [0, 10000] -> [0.5, 2.5]."""
    x = rng.uniform(0, 10000, size=(32, 32)).astype(np.float32)
    y = np.asarray(normalize(jnp.asarray(x)))
    expected = x / 10000.0 * 2.0 + 0.5
    np.testing.assert_allclose(y, expected, rtol=1e-6)
    assert np.asarray(normalize(jnp.float32(0.0))) == 0.5
    assert np.asarray(normalize(jnp.float32(10000.0))) == 2.5


def test_normalize_extrapolates_outside_window():
    # no clamping inside normalize — that's clip_intensity's job
    assert float(normalize(jnp.float32(20000.0))) > 2.5


def test_clip_reference_params(rng):
    x = rng.uniform(-1, 5000, size=(16, 16)).astype(np.float32)
    y = np.asarray(clip_intensity(jnp.asarray(x)))
    np.testing.assert_allclose(y, np.clip(x, 0.68, 4000.0))


def test_cast_uint8():
    x = jnp.array([[0.0, 1.0, 1.9, 255.0]])
    y = cast_uint8(x)
    assert y.dtype == jnp.uint8
    np.testing.assert_array_equal(np.asarray(y), [[0, 1, 1, 255]])


def test_binary_threshold():
    x = jnp.array([0.5, 0.74, 0.8, 0.91, 0.95])
    y = np.asarray(binary_threshold(x, 0.74, 0.91))
    np.testing.assert_array_equal(y, [0, 1, 1, 1, 0])


def test_elementwise_chain_jits_and_fuses():
    f = jax.jit(lambda x: clip_intensity(normalize(x)))
    x = jnp.full((8, 8), 5000.0)
    np.testing.assert_allclose(np.asarray(f(x)), 1.5)
