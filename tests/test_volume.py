"""3D volumetric ops vs scipy oracles + volume pipeline integration.

Formalizes the volumetric capability (BASELINE.json config 4) the same way
the 2D suite formalizes the reference's per-slice contract: each kernel is
property-tested against a scipy.ndimage oracle, and the full volume pipeline
is checked to segment a phantom lesion as one connected 3D body.
"""

import numpy as np
import pytest
from scipy import ndimage

import jax.numpy as jnp

from nm03_capstone_project_tpu.data.synthetic import phantom_volume
from nm03_capstone_project_tpu.ops.volume import (
    dilate3d,
    erode3d,
    footprint_offsets_3d,
    region_grow_3d,
)


def _structure(connectivity):
    # scipy's generate_binary_structure(3, 1) is the 6-connected cross,
    # (3, 3) the full 26-connected cube
    return ndimage.generate_binary_structure(3, 1 if connectivity == 6 else 3)


class TestFootprints:
    def test_cross_size3_is_6_connected(self):
        offs = footprint_offsets_3d(3, "cross")
        assert len(offs) == 7  # center + 6 face neighbors
        assert (0, 0, 0) in offs

    def test_box_size3_is_26_connected(self):
        assert len(footprint_offsets_3d(3, "box")) == 27

    def test_unknown_shape_raises(self):
        with pytest.raises(ValueError):
            footprint_offsets_3d(3, "banana")


class TestMorphology3D:
    @pytest.mark.parametrize("shape,conn", [("cross", 6), ("box", 26)])
    def test_dilate_matches_scipy(self, rng, shape, conn):
        x = (rng.random((6, 12, 12)) > 0.7).astype(np.uint8)
        got = np.asarray(dilate3d(jnp.asarray(x), 3, shape))
        want = ndimage.binary_dilation(x, structure=_structure(conn)).astype(np.uint8)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("shape,conn", [("cross", 6), ("box", 26)])
    def test_erode_matches_scipy(self, rng, shape, conn):
        x = (rng.random((6, 12, 12)) > 0.3).astype(np.uint8)
        got = np.asarray(erode3d(jnp.asarray(x), 3, shape))
        # outside-volume counts as background, so border foreground erodes:
        # scipy equivalent is border_value=0 (its default)
        want = ndimage.binary_erosion(x, structure=_structure(conn)).astype(np.uint8)
        np.testing.assert_array_equal(got, want)

    def test_bool_dtype_round_trips(self, rng):
        x = rng.random((4, 8, 8)) > 0.5
        assert np.asarray(dilate3d(jnp.asarray(x))).dtype == np.bool_

    def test_batch_axis_vmaps(self, rng):
        x = (rng.random((2, 4, 8, 8)) > 0.6).astype(np.uint8)
        got = np.asarray(dilate3d(jnp.asarray(x)))
        for b in range(2):
            np.testing.assert_array_equal(
                got[b], np.asarray(dilate3d(jnp.asarray(x[b])))
            )


from tests.oracles import region_grow_oracle as _oracle_region_grow  # noqa: E402


class TestRegionGrow3D:
    @pytest.mark.parametrize("connectivity", [6, 26])
    def test_matches_connected_component_oracle(self, rng, connectivity):
        vol = rng.random((8, 16, 16)).astype(np.float32)
        seeds = np.zeros_like(vol, dtype=bool)
        seeds[4, 8, 8] = True
        seeds[2, 3, 12] = True
        got = np.asarray(
            region_grow_3d(
                jnp.asarray(vol),
                jnp.asarray(seeds),
                0.4,
                0.9,
                connectivity=connectivity,
                block_iters=4,
            )[0]
        )
        want = _oracle_region_grow(vol, seeds, 0.4, 0.9, connectivity)
        np.testing.assert_array_equal(got, want)

    def test_z_connectivity_crosses_slices(self):
        # two in-band blobs on adjacent slices that only touch through z
        vol = np.zeros((3, 8, 8), np.float32)
        vol[0, 2:4, 2:4] = 0.5
        vol[1, 3, 3] = 0.5  # overlaps (3,3) of slice 0 through z
        vol[2, 6, 6] = 0.5  # in band but not connected
        seeds = np.zeros_like(vol, dtype=bool)
        seeds[0, 2, 2] = True
        got = np.asarray(
            region_grow_3d(jnp.asarray(vol), jnp.asarray(seeds), 0.4, 0.6)[0]
        )
        assert got[1, 3, 3] == 1  # reached through z
        assert got[2, 6, 6] == 0  # disconnected blob untouched

    def test_valid_mask_blocks_padding(self):
        vol = np.full((2, 6, 6), 0.5, np.float32)
        seeds = np.zeros_like(vol, dtype=bool)
        seeds[0, 1, 1] = True
        valid = np.zeros_like(vol, dtype=bool)
        valid[:, :3, :3] = True
        got = np.asarray(
            region_grow_3d(
                jnp.asarray(vol), jnp.asarray(seeds), 0.4, 0.6,
                valid=jnp.asarray(valid),
            )[0]
        )
        assert got[:, :3, :3].sum() == 18
        assert got[:, 3:, :].sum() == 0 and got[:, :, 3:].sum() == 0


class TestRegionGrowJump3D:
    """3D pointer-jumping schedule: same sets as the dilate fixpoint."""

    @pytest.mark.parametrize("connectivity", [6, 26])
    def test_matches_oracle_and_dilate(self, rng, connectivity):
        from nm03_capstone_project_tpu.ops import region_grow_jump_3d

        vol = rng.random((8, 16, 16)).astype(np.float32)
        seeds = np.zeros_like(vol, dtype=bool)
        seeds[4, 8, 8] = True
        seeds[2, 3, 12] = True
        got = np.asarray(
            region_grow_jump_3d(
                jnp.asarray(vol), jnp.asarray(seeds), 0.4, 0.9,
                connectivity=connectivity,
            )[0]
        )
        np.testing.assert_array_equal(
            got, _oracle_region_grow(vol, seeds, 0.4, 0.9, connectivity)
        )

    @pytest.mark.slow
    def test_helix_path_through_z(self):
        # a path winding through all three axes: worst case for one-shell
        # growth, routine for the O(log) schedule
        vol = np.zeros((6, 10, 10), np.float32)
        for z in range(6):
            if z % 2 == 0:
                vol[z, z % 10, :9] = 0.5
            else:
                vol[z, z % 10, 8] = 0.5
                vol[z, (z + 1) % 10, 8] = 0.5
            if z + 1 < 6:  # connect to next slice
                vol[z, (z + 1) % 10, 0 if z % 2 else 8] = 0.5
                vol[z + 1, (z + 1) % 10, 0 if z % 2 else 8] = 0.5
        seeds = np.zeros_like(vol, dtype=bool)
        seeds[0, 0, 0] = True
        from nm03_capstone_project_tpu.ops import region_grow_jump_3d

        got = np.asarray(
            region_grow_jump_3d(jnp.asarray(vol), jnp.asarray(seeds), 0.4, 0.6)[0]
        )
        np.testing.assert_array_equal(got, _oracle_region_grow(vol, seeds, 0.4, 0.6, 6))

    @pytest.mark.slow
    def test_volume_pipeline_with_jump_matches_default(self):
        import dataclasses

        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.data.synthetic import phantom_volume
        from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

        cfg = PipelineConfig(grow_block_iters=8, grow_max_iters=512)
        cfg_jump = dataclasses.replace(cfg, grow_algorithm="jump")
        vol = jnp.asarray(phantom_volume(n_slices=8, height=48, width=48, seed=2))
        dims = jnp.asarray([48, 48], np.int32)
        a = process_volume(vol, dims, cfg)
        b = process_volume(vol, dims, cfg_jump)
        np.testing.assert_array_equal(np.asarray(a["mask"]), np.asarray(b["mask"]))
        assert np.asarray(a["mask"]).sum() > 0

    def test_rejects_batched_input(self):
        from nm03_capstone_project_tpu.ops import region_grow_jump_3d

        with pytest.raises(ValueError, match="per-volume"):
            region_grow_jump_3d(
                np.zeros((2, 4, 8, 8), np.float32),
                np.zeros((2, 4, 8, 8), bool),
                0.0,
                1.0,
            )


class TestVolumePipeline:
    @pytest.mark.slow
    def test_phantom_lesion_segmented_as_one_body(self):
        from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

        vol = phantom_volume(n_slices=8, height=96, width=96, seed=1)
        out = process_volume(jnp.asarray(vol), jnp.asarray([96, 96], jnp.int32))
        mask = np.asarray(out["mask"])
        assert mask.shape == vol.shape
        assert mask.dtype == np.uint8
        assert mask.sum() > 0
        # the dilated lesion forms one 6-connected component
        labels, n = ndimage.label(
            mask, structure=ndimage.generate_binary_structure(3, 1)
        )
        assert n == 1
        # mask present on several central slices (lesion waxes/wanes)
        per_slice = mask.reshape(mask.shape[0], -1).sum(axis=1)
        assert (per_slice > 0).sum() >= 3

    @pytest.mark.slow
    def test_respects_canvas_padding(self):
        from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

        vol = phantom_volume(n_slices=4, height=64, width=64, seed=2)
        canvas = np.zeros((4, 96, 96), np.float32)
        canvas[:, :64, :64] = vol
        out = process_volume(jnp.asarray(canvas), jnp.asarray([64, 64], jnp.int32))
        mask = np.asarray(out["mask"])
        assert mask[:, 64:, :].sum() == 0
        assert mask[:, :, 64:].sum() == 0


class TestConvergedFlag3D:
    """VERDICT r4 item 4, 3D paths: cap-truncation must be detected."""

    def test_capped_detected_and_full_converges(self):
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.ops import region_grow_3d, region_grow_jump_3d

        vol = np.full((8, 24, 24), 0.8, np.float32)
        seeds = np.zeros((8, 24, 24), bool)
        seeds[0, 0, 0] = True
        mask, conv = region_grow_3d(
            jnp.asarray(vol), jnp.asarray(seeds), 0.74, 0.91,
            block_iters=2, max_iters=4,
        )
        assert not bool(conv)
        assert 0 < np.asarray(mask).sum() < vol.size
        mask2, conv2 = region_grow_3d(
            jnp.asarray(vol), jnp.asarray(seeds), 0.74, 0.91,
            block_iters=16, max_iters=512,
        )
        assert bool(conv2) and np.asarray(mask2).sum() == vol.size
        mask3, conv3 = region_grow_jump_3d(
            jnp.asarray(vol), jnp.asarray(seeds), 0.74, 0.91
        )
        assert bool(conv3) and np.asarray(mask3).sum() == vol.size

    def test_process_volume_surfaces_flag(self):
        import dataclasses

        import jax.numpy as jnp

        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume
        from nm03_capstone_project_tpu.data.synthetic import phantom_series

        cfg = PipelineConfig(canvas=64)
        series = phantom_series(4, 64, 64, seed=9)
        vol = np.stack(series).astype(np.float32)
        dims = jnp.asarray([64, 64], np.int32)
        out = process_volume(jnp.asarray(vol), dims, cfg)
        assert bool(np.asarray(out["grow_converged"]))
        capped_cfg = dataclasses.replace(
            cfg, grow_block_iters=1, grow_max_iters=2
        )
        out2 = process_volume(jnp.asarray(vol), dims, capped_cfg)
        assert not bool(np.asarray(out2["grow_converged"]))
