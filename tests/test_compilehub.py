"""Compile-hub subsystem tests (ISSUE 6 tentpole).

The compat shim (the only sanctioned ``shard_map``/``pjit`` home), the
spec registry's caching/accounting contract, and the mesh-aware program
builders — per-lane pinned AOT serving executables included. Runs on the
8-virtual-device CPU mesh the conftest pins.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.compilehub import (
    CompileHub,
    CompileSpec,
    aot_compile,
    distributed_is_initialized,
    get_hub,
    hub_jit,
    programs,
    shard_map,
)
from nm03_capstone_project_tpu.config import PipelineConfig

CFG = PipelineConfig(canvas=64, grow_block_iters=4, grow_max_iters=64)


class TestCompatShim:
    def test_shard_map_resolves_and_runs_collectives(self):
        """The shim must resolve on THIS jax (the seed failed here: a direct
        jax.shard_map reference on a jaxlib shipping only the experimental
        entry point) and run a real psum over the mesh."""
        from jax.sharding import PartitionSpec as P

        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(8, axis_names=("z",))
        f = shard_map(
            lambda x: jax.lax.psum(x.sum(), "z"),
            mesh=mesh,
            in_specs=P("z"),
            out_specs=P(),
            check_vma=False,
        )
        assert float(f(jnp.ones(8, jnp.float32))) == 8.0

    def test_check_vma_default_accepted(self):
        from jax.sharding import PartitionSpec as P

        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(8, axis_names=("z",))
        f = shard_map(
            lambda x: x * 2, mesh=mesh, in_specs=P("z"), out_specs=P("z")
        )
        np.testing.assert_array_equal(
            np.asarray(f(jnp.ones(8))), np.full(8, 2.0, np.float32)
        )

    def test_distributed_is_initialized_single_process(self):
        assert distributed_is_initialized() is False

    def test_compat_is_the_only_shard_map_importer(self):
        """The NM361 contract, asserted structurally: no module outside
        compilehub/ references jax's jit/pjit/shard_map without a reasoned
        suppression (the lint gate enforces the same; this drill keeps the
        invariant failing loudly even in environments that skip the gate).
        """
        from pathlib import Path

        from nm03_capstone_project_tpu.analysis.compilehome import (
            check_compile_home,
        )
        from nm03_capstone_project_tpu.analysis.core import (
            collect_files,
            run_rules,
        )

        root = Path(__file__).parents[1]
        files = collect_files(
            [root / "nm03_capstone_project_tpu", root / "bench.py"], root
        )
        findings = run_rules(files, (check_compile_home,))
        assert findings == [], [f.render() for f in findings]


class TestHubRegistry:
    def test_same_spec_returns_cached_executable(self):
        hub = CompileHub()
        built = []

        def build(spec):
            built.append(spec)
            return lambda x: x + 1

        s = CompileSpec(name="t", shape=(4,))
        f1 = hub.get(s, build)
        f2 = hub.get(s, build)
        assert f1 is f2 and len(built) == 1
        assert hub.stats()["executables"] == 1
        assert hub.stats()["builds"] == 1

    def test_distinct_specs_build_separately(self):
        hub = CompileHub()
        f1 = hub.get(CompileSpec(name="t", shape=(1,)), lambda s: "a")
        f2 = hub.get(CompileSpec(name="t", shape=(2,)), lambda s: "b")
        f4 = hub.get(CompileSpec(name="t", shape=(1,), lane=3), lambda s: "c")
        assert (f1, f2, f4) == ("a", "b", "c")
        assert hub.stats()["executables"] == 3

    def test_peek_and_drop(self):
        hub = CompileHub()
        s = CompileSpec(name="t")
        assert hub.peek(s) is None
        hub.get(s, lambda spec: "x")
        assert hub.peek(s) == "x"
        hub.drop(s)
        assert hub.peek(s) is None

    def test_aot_tuple_recorded(self):
        hub = CompileHub()
        jitted = hub_jit(lambda x: x * 2)
        s = CompileSpec(name="aot", shape=(4,))
        fn = hub.get(
            s,
            lambda spec: aot_compile(
                jitted, jax.ShapeDtypeStruct((4,), jnp.float32)
            ),
        )
        assert float(fn(np.ones(4, np.float32)).sum()) == 8.0
        assert hub.stats()["aot"] == 1

    def test_process_hub_is_shared(self):
        assert get_hub() is get_hub()


class TestServeLanePrograms:
    def test_lane_devices_cap_and_overflow(self):
        devs = programs.lane_devices()
        assert len(devs) == 8  # conftest's virtual mesh
        assert len(programs.lane_devices(3)) == 3
        with pytest.raises(ValueError, match="lanes"):
            programs.lane_devices(99)

    def test_pinned_executables_land_on_their_lane(self):
        devs = programs.lane_devices()
        px = np.zeros((2, 64, 64), np.float32)
        dm = np.full((2, 2), 8, np.int32)
        outs = {}
        for lane in (0, 5):
            ex = programs.serve_mask(CFG, bucket=2, device=devs[lane])
            mask, conv = ex(px, dm)
            assert mask.devices() == {devs[lane]}
            outs[lane] = np.asarray(mask)
        np.testing.assert_array_equal(outs[0], outs[5])

    def test_spec_cache_hits_per_lane_and_bucket(self):
        devs = programs.lane_devices()
        a = programs.serve_mask(CFG, bucket=2, device=devs[0])
        assert programs.serve_mask(CFG, bucket=2, device=devs[0]) is a
        assert programs.serve_mask(CFG, bucket=4, device=devs[0]) is not a
        assert programs.serve_mask(CFG, bucket=2, device=devs[1]) is not a

    def test_deferred_variant_without_bucket(self):
        fn = programs.serve_mask(CFG)  # CPU-degradation target: retrace ok
        mask, conv = fn(
            np.zeros((3, 64, 64), np.float32), np.full((3, 2), 8, np.int32)
        )
        assert np.asarray(mask).shape == (3, 64, 64)


class TestDriverProgramsShareTheHub:
    def test_runner_fns_are_hub_programs(self):
        from nm03_capstone_project_tpu.cli.runner import (
            _compiled_batch_mask_fn,
            _compiled_slice_mask_fn,
        )

        assert _compiled_batch_mask_fn(CFG) is _compiled_batch_mask_fn(CFG)
        assert _compiled_slice_mask_fn(CFG) is _compiled_slice_mask_fn(CFG)

    def test_volume_fns_are_hub_programs(self):
        from nm03_capstone_project_tpu.cli.volume import (
            _compiled_render_fn,
            _compiled_volume_mask_fn,
        )

        assert _compiled_volume_mask_fn(CFG) is _compiled_volume_mask_fn(CFG)
        assert _compiled_render_fn(CFG) is _compiled_render_fn(CFG)

    def test_volume_variant_rejects_unknown(self):
        with pytest.raises(ValueError, match="variant"):
            programs.volume_pipeline(CFG, "bogus")
