"""Online serving subsystem tests (ISSUE 4).

Covers the contract end to end: admission-queue backpressure semantics,
dynamic-batcher coalescing/padding (against a fake executor — no jax),
loopback HTTP round trips on an ephemeral port (synthetic slice in, JPEG
pair bytes out), shed-under-overload with ``Retry-After``, the degraded
``/readyz`` contract, a fault-plan chaos run through the serving path
(transient retry + hang -> one-way CPU degradation), SIGTERM graceful
drain in a real subprocess, and the loadgen smoke whose metrics snapshot
``check_telemetry.py`` gates with the new ``--expect-histogram`` hook.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_slice
from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.queue import (
    AdmissionQueue,
    QueueClosed,
    QueueFull,
    ServeRequest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")

CANVAS = 128


def _post(url: str, body: bytes, headers: dict, timeout=30.0):
    """POST; returns (status, parsed json, headers) without raising on 4xx/5xx."""
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _get(url: str, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _raw_headers(h: int, w: int) -> dict:
    return {
        "Content-Type": "application/octet-stream",
        "X-Nm03-Height": str(h),
        "X-Nm03-Width": str(w),
    }


def _phantom_body(h: int = CANVAS, w: int = CANVAS, seed: int = 0) -> bytes:
    return phantom_slice(h, w, seed=seed).astype("<f4").tobytes()


def run_checker(*argv):
    return subprocess.run(
        [sys.executable, CHECKER, *map(str, argv)],
        capture_output=True, text=True, timeout=60,
    )


# -- admission queue (pure stdlib, no jax) ---------------------------------


def _req(i: int = 0) -> ServeRequest:
    return ServeRequest(
        request_id=f"r{i}", pixels=np.zeros((8, 8), np.float32), dims=(8, 8)
    )


class TestAdmissionQueue:
    def test_capacity_bound_sheds(self):
        q = AdmissionQueue(2)
        q.put(_req(0))
        q.put(_req(1))
        with pytest.raises(QueueFull):
            q.put(_req(2))
        assert len(q) == 2

    def test_close_refuses_but_drains_tail(self):
        q = AdmissionQueue(4)
        q.put(_req(0))
        q.close()
        with pytest.raises(QueueClosed):
            q.put(_req(1))
        # the admitted tail still comes out...
        batch = q.get_batch(max_batch=4, max_wait_s=0.0)
        assert [r.request_id for r in batch] == ["r0"]
        # ...and an empty closed queue signals drain-complete
        assert q.get_batch(max_batch=4, max_wait_s=0.0) == []

    def test_get_batch_coalesces_backlog(self):
        q = AdmissionQueue(8)
        for i in range(3):
            q.put(_req(i))
        batch = q.get_batch(max_batch=8, max_wait_s=0.0)
        assert [r.request_id for r in batch] == ["r0", "r1", "r2"]

    def test_get_batch_respects_max_batch(self):
        q = AdmissionQueue(8)
        for i in range(5):
            q.put(_req(i))
        assert len(q.get_batch(max_batch=2, max_wait_s=0.0)) == 2
        assert len(q) == 3

    def test_get_batch_window_waits_for_riders(self):
        q = AdmissionQueue(8)
        q.put(_req(0))

        def late_rider():
            time.sleep(0.05)
            q.put(_req(1))

        t = threading.Thread(target=late_rider)
        t.start()
        batch = q.get_batch(max_batch=8, max_wait_s=0.5)
        t.join()
        assert len(batch) == 2

    def test_zero_capacity_rejected(self):
        with pytest.raises(ValueError):
            AdmissionQueue(0)


# -- dynamic batcher against a fake executor (no jax) ----------------------


class FakeExecutor:
    """Executor stand-in recording the padded batches it was handed."""

    def __init__(self, buckets=(1, 2, 4), canvas=16, min_dim=4, fail=None):
        self.cfg = SimpleNamespace(canvas=canvas, min_dim=min_dim)
        self.buckets = tuple(buckets)
        self.fail = fail
        self.calls = []

    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run_batch(self, pixels, dims):
        self.calls.append((pixels.copy(), dims.copy()))
        if self.fail is not None:
            raise self.fail
        # mask = 1 wherever the input was > 0 (so crops are checkable)
        mask = (pixels > 0).astype(np.uint8)
        return mask, np.ones(pixels.shape[0], bool)


class TestDynamicBatcher:
    def _reqs(self, sizes):
        out = []
        for i, (h, w) in enumerate(sizes):
            out.append(
                ServeRequest(
                    request_id=f"r{i}",
                    pixels=np.ones((h, w), np.float32),
                    dims=(h, w),
                )
            )
        return out

    def test_pads_to_smallest_bucket(self):
        ex = FakeExecutor()
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = self._reqs([(8, 8), (6, 10), (16, 16)])
        b.execute(reqs)
        (pixels, dims), = ex.calls
        assert pixels.shape == (4, 16, 16)  # 3 requests -> bucket 4
        assert dims.tolist()[:3] == [[8, 8], [6, 10], [16, 16]]
        # dead lane: zero pixels, min_dim dims
        assert pixels[3].sum() == 0 and dims[3].tolist() == [4, 4]

    def test_results_cropped_and_distributed(self):
        ex = FakeExecutor()
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = self._reqs([(8, 8), (6, 10)])
        b.execute(reqs)
        for r in reqs:
            assert r.done.is_set() and r.error is None
            assert r.mask.shape == r.dims
            assert r.mask.all()  # input was all-ones -> mask all-ones
            assert r.batch_size == 2

    def test_executor_failure_fails_every_rider(self):
        ex = FakeExecutor(fail=RuntimeError("boom"))
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = self._reqs([(8, 8), (8, 8)])
        b.execute(reqs)
        for r in reqs:
            assert r.done.is_set()
            assert isinstance(r.error, RuntimeError)

    def test_thread_coalesces_concurrent_submissions(self):
        ex = FakeExecutor(buckets=(1, 2, 4, 8))
        q = AdmissionQueue(16)
        b = DynamicBatcher(q, ex, max_wait_s=0.1).start()
        reqs = self._reqs([(8, 8)] * 6)
        for r in reqs:
            q.put(r)
        for r in reqs:
            assert r.wait(5.0)
        q.close()
        assert b.join(5.0)
        assert max(r.batch_size for r in reqs) > 1

    def test_max_batch_above_buckets_rejected(self):
        ex = FakeExecutor(buckets=(1, 2))
        with pytest.raises(ValueError, match="largest warm bucket"):
            DynamicBatcher(AdmissionQueue(4), ex, max_batch=8)


class TestExecutorBuckets:
    def test_bucket_for_and_validation(self):
        from nm03_capstone_project_tpu.serving.executor import WarmExecutor

        ex = WarmExecutor(PipelineConfig(canvas=CANVAS), buckets=(1, 2, 4))
        assert [ex.bucket_for(n) for n in (1, 2, 3, 4)] == [1, 2, 4, 4]
        with pytest.raises(ValueError, match="exceeds the largest"):
            ex.bucket_for(5)
        with pytest.raises(ValueError, match="strictly increasing"):
            WarmExecutor(PipelineConfig(), buckets=(4, 2))
        with pytest.raises(ValueError, match=">= 1"):
            WarmExecutor(PipelineConfig(), buckets=(0, 1))


# -- loopback end-to-end ----------------------------------------------------


@pytest.fixture(scope="module")
def served():
    """One warmed loopback server shared by the e2e tests (3 compiles).

    lanes=1 on purpose: these are the single-lane regression tests (the
    PR-4 contract must survive the fleet); the multi-lane fan-out path has
    its own suite in tests/test_serving_lanes.py.
    """
    from nm03_capstone_project_tpu.serving.server import ServingApp, serve_in_thread

    app = ServingApp(
        cfg=PipelineConfig(canvas=CANVAS),
        queue_capacity=32,
        buckets=(1, 2, 4),
        max_wait_s=0.02,
        request_timeout_s=30.0,
        lanes=1,
    )
    httpd, _, port = serve_in_thread(app)
    yield app, f"http://127.0.0.1:{port}"
    app.begin_drain(reason="test_teardown")
    httpd.shutdown()
    httpd.server_close()
    app.close()


class TestLoopbackE2E:
    def test_health_and_ready(self, served):
        app, base = served
        status, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body)["status"] == "alive"
        status, body = _get(base + "/readyz")
        st = json.loads(body)
        assert status == 200 and st["ready"] and st["warm"]

    def test_synthetic_slice_to_jpeg_pair(self, served):
        app, base = served
        status, payload, headers = _post(
            base + "/v1/segment", _phantom_body(), _raw_headers(CANVAS, CANVAS)
        )
        assert status == 200
        orig = base64.b64decode(payload["original_jpeg_b64"])
        proc = base64.b64decode(payload["processed_jpeg_b64"])
        # JPEG SOI marker on both legs of the pair; EOI closes each stream
        # (a torn/partial encode could never reach the wire)
        for blob in (orig, proc):
            assert blob[:2] == b"\xff\xd8" and blob[-2:] == b"\xff\xd9"
        assert payload["mask_pixels"] > 0
        assert payload["grow_converged"] is True
        assert headers["X-Nm03-Batch-Size"] == str(payload["batch_size"])

    def test_dicom_body_matches_raw(self, served, tmp_path):
        """The full-parser ingress route produces the same mask as raw."""
        app, base = served
        img = phantom_slice(CANVAS, CANVAS, seed=3)
        status, raw_payload, _ = _post(
            base + "/v1/segment?output=mask",
            img.astype("<f4").tobytes(),
            _raw_headers(CANVAS, CANVAS),
        )
        assert status == 200
        from nm03_capstone_project_tpu.data.dicomlite import write_dicom

        path = tmp_path / "slice.dcm"
        write_dicom(path, np.clip(img, 0, 65535).astype(np.uint16))
        status, dcm_payload, _ = _post(
            base + "/v1/segment?output=mask",
            path.read_bytes(),
            {"Content-Type": "application/dicom"},
        )
        assert status == 200
        assert dcm_payload["mask_pixels"] == raw_payload["mask_pixels"]

    def test_rejections(self, served):
        app, base = served
        # below min_dim
        status, body, _ = _post(
            base + "/v1/segment", b"\0" * (40 * 40 * 4), _raw_headers(40, 40)
        )
        assert status == 400 and "minimum dimension" in body["error"]
        # above canvas: the declared dims alone must reject (413), before
        # the body-size cap even matters
        status, body, _ = _post(
            base + "/v1/segment",
            b"\0" * (200 * 200 * 4),
            _raw_headers(200, 200),
        )
        assert status == 413
        # wrong byte count for the declared dims
        status, body, _ = _post(
            base + "/v1/segment", b"\0" * 100, _raw_headers(CANVAS, CANVAS)
        )
        assert status == 400
        # no recognizable content type and no dim headers
        status, body, _ = _post(
            base + "/v1/segment", b"\0" * 100, {"Content-Type": "text/plain"}
        )
        assert status == 415
        # malformed DICOM through the real parser
        status, body, _ = _post(
            base + "/v1/segment", b"not a dicom file",
            {"Content-Type": "application/dicom"},
        )
        assert status == 400 and "DICOM parse failed" in body["error"]

    def test_concurrent_requests_coalesce(self, served):
        app, base = served
        results = []
        lock = threading.Lock()

        def one(i):
            status, payload, _ = _post(
                base + "/v1/segment?output=mask",
                _phantom_body(seed=i % 3),
                _raw_headers(CANVAS, CANVAS),
            )
            with lock:
                results.append((status, payload.get("batch_size", 0)))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 12
        assert all(s == 200 for s, _ in results)
        # the acceptance bar: coalescing actually happened
        assert max(bs for _, bs in results) > 1

    def test_metrics_endpoints(self, served, tmp_path):
        app, base = served
        status, prom = _get(base + "/metrics")
        assert status == 200
        text = prom.decode()
        for name in (
            "serving_requests_total",
            "serving_batch_size_bucket",
            "serving_queue_wait_seconds_bucket",
            "serving_request_seconds_bucket",
        ):
            assert name in text, f"{name} missing from /metrics"
        status, snap = _get(base + "/metrics.json")
        assert status == 200
        path = tmp_path / "serve_metrics.json"
        path.write_bytes(snap)
        res = run_checker(
            "--metrics", path,
            "--expect-counter", "serving_requests_total=10",
            "--expect-counter", "serving_batches_total=1",
            "--expect-histogram", "serving_queue_wait_seconds=10",
            "--expect-histogram", "serving_batch_size=1",
            "--expect-histogram", "serving_request_seconds=10",
        )
        assert res.returncode == 0, res.stderr


# -- shed / drain on an unstarted app (no batcher -> deterministic) ---------


@pytest.fixture()
def stalled_server():
    """A bound server whose batcher never starts: every admitted request
    parks until its (short) timeout, so overload is deterministic."""
    from nm03_capstone_project_tpu.serving.server import ServingApp, make_http_server

    app = ServingApp(
        cfg=PipelineConfig(canvas=CANVAS),
        queue_capacity=1,
        buckets=(1,),
        max_wait_s=0.0,
        request_timeout_s=0.6,
    )
    httpd = make_http_server(app)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield app, f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()
    app.close()


class TestBackpressure:
    def test_readyz_not_warm(self, stalled_server):
        app, base = stalled_server
        status, body = _get(base + "/readyz")
        st = json.loads(body)
        assert status == 503 and not st["warm"] and not st["ready"]

    def test_shed_past_queue_bound(self, stalled_server):
        app, base = stalled_server
        first_status = {}

        def occupier():
            s, body, _ = _post(
                base + "/v1/segment?output=mask",
                _phantom_body(),
                _raw_headers(CANVAS, CANVAS),
                timeout=10.0,
            )
            first_status["code"] = s

        t = threading.Thread(target=occupier)
        t.start()
        deadline = time.monotonic() + 2.0
        while len(app.queue) == 0 and time.monotonic() < deadline:
            time.sleep(0.01)  # wait until the occupier holds the only slot
        status, body, headers = _post(
            base + "/v1/segment?output=mask",
            _phantom_body(seed=1),
            _raw_headers(CANVAS, CANVAS),
        )
        t.join(timeout=10)
        assert status == 503
        assert headers.get("Retry-After") == "1"
        assert first_status["code"] == 504  # the occupier timed out cleanly
        reg = app.registry
        assert reg.get("serving_shed_total").value >= 1
        assert reg.get("serving_requests_total", status="shed").value >= 1
        assert reg.get("serving_requests_total", status="timeout").value >= 1

    def test_drain_refuses_with_retry_after(self, stalled_server):
        app, base = stalled_server
        assert app.begin_drain(reason="test") is True
        status, body, headers = _post(
            base + "/v1/segment?output=mask",
            _phantom_body(),
            _raw_headers(CANVAS, CANVAS),
        )
        assert status == 503 and body["draining"] is True
        assert headers.get("Retry-After") == "1"
        events = [r["event"] for r in app.obs.events.tail]
        assert "serving_drain" in events
        drain_rec = next(
            r for r in app.obs.events.tail if r["event"] == "serving_drain"
        )
        assert drain_rec["level"] == "WARNING"
        # idempotent
        assert app.begin_drain(reason="again") is True


class TestDegradedReadyz:
    def test_degraded_flips_ready_off(self):
        """Process-wide degradation (EVERY lane quarantined, ISSUE 8) is
        still the one state that pulls /readyz to 503."""
        from nm03_capstone_project_tpu.serving.server import ServingApp

        app = ServingApp(cfg=PipelineConfig(canvas=CANVAS), buckets=(1,))
        app.executor.warm = True  # pretend warmup ran; no jax needed
        assert app.ready
        app.executor._process_degrade("deadline")
        assert not app.ready
        st = app.status()
        assert st["degraded"] and st["degraded_cause"] == "deadline"
        app.close()

    def test_partial_quarantine_keeps_ready_at_reduced_capacity(self):
        """A quarantined lane (not all of them) must NOT pull the replica
        out of the balancer: /readyz stays 200 and reports the healthy
        fraction in ``capacity`` + ``lanes.quarantined`` (ISSUE 8)."""
        from nm03_capstone_project_tpu.serving.lanes import LaneFaultDomains
        from nm03_capstone_project_tpu.serving.server import ServingApp

        app = ServingApp(cfg=PipelineConfig(canvas=CANVAS), buckets=(1,))
        ex = app.executor
        ex.warm = True
        # simulate a resolved 4-lane fleet without touching a backend
        ex._lane_devices = ["d0", "d1", "d2", "d3"]
        ex._lane_warm = [True] * 4
        ex._lane_inflight = [0] * 4
        ex._lane_batches = [0] * 4
        ex._lane_supervisors = [ex._new_supervisor() for _ in range(4)]
        ex.fleet = LaneFaultDomains(4, obs=app.obs)
        assert app.status()["capacity"] == 1.0
        changed, healthy_left = ex.fleet.quarantine(2, "deadline")
        assert changed and healthy_left == 3
        assert app.ready  # 3 healthy chips are 75% of a replica, not zero
        st = app.status()
        assert st["capacity"] == 0.75
        assert st["lanes"]["quarantined"] == 1
        assert not st["degraded"]
        per_lane = {row["lane"]: row for row in st["lanes"]["per_lane"]}
        assert per_lane[2]["state"] == "quarantined"
        assert per_lane[2]["quarantine_cause"] == "deadline"
        assert per_lane[0]["state"] == "healthy"
        assert app.registry.get("serving_lane_state", lane="2").value == 2
        assert (
            app.registry.get(
                "serving_lane_quarantines_total", lane="2", cause="deadline"
            ).value
            == 1
        )
        app.close()


# -- chaos through the serving path ----------------------------------------


class TestServingChaos:
    def test_transient_retry_then_hang_degrades_to_cpu(self):
        """The PR-3 ladder under online traffic: request 1 eats a transient
        fault and retries to success; request 2 eats an injected hang, the
        dispatch deadline abandons it, the service degrades one-way to the
        CPU fallback and KEEPS ANSWERING; /readyz reflects the degradation.
        """
        from nm03_capstone_project_tpu.resilience import FaultPlan, ResilienceConfig
        from nm03_capstone_project_tpu.serving.server import ServingApp

        plan = FaultPlan.from_spec(json.dumps({
            "seed": 11,
            "faults": [
                {"site": "dispatch", "kind": "transient", "count": 1},
                {"site": "dispatch", "kind": "hang", "hang_s": 30.0,
                 "after": 2, "count": 1},
            ],
        }))
        app = ServingApp(
            cfg=PipelineConfig(canvas=CANVAS),
            buckets=(1,),
            max_wait_s=0.0,
            resilience=ResilienceConfig(
                retry_max=2, retry_backoff_s=0.01, dispatch_timeout_s=1.0
            ),
            fault_plan=plan,
            lanes=1,  # deterministic dispatch indices for the fault plan
        )
        app.start()
        try:
            img = phantom_slice(CANVAS, CANVAS, seed=0)
            # request 1: transient -> retried inside the deadline -> ok
            p1 = app.segment(img, render=False)
            assert p1["mask_pixels"] > 0 and not p1["degraded"]
            # request 2: hang -> deadline expiry -> one-way CPU degradation
            p2 = app.segment(img, render=False)
            assert p2["mask_pixels"] == p1["mask_pixels"]  # same math on CPU
            assert p2["degraded"] is True
            assert not app.ready  # /readyz contract
            # request 3: straight to the (already-warm) fallback
            p3 = app.segment(img, render=False)
            assert p3["mask_pixels"] == p1["mask_pixels"]
            reg = app.registry
            assert reg.get("resilience_retries_total", cause="serve_dispatch").value >= 1
            assert reg.get("pipeline_degraded_total", cause="deadline").value == 1
            assert (
                reg.get("resilience_faults_injected_total",
                        site="dispatch", kind="transient").value == 1
            )
            assert (
                reg.get("resilience_faults_injected_total",
                        site="dispatch", kind="hang").value == 1
            )
        finally:
            app.begin_drain(reason="test")
            app.close()


# -- SIGTERM graceful drain (real process) ----------------------------------


class TestSigtermDrain:
    def test_sigterm_drains_and_flushes(self, tmp_path):
        port_file = tmp_path / "port"
        metrics = tmp_path / "metrics.json"
        events = tmp_path / "events.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1", "--lanes", "1",
                "--max-wait-ms", "5", "--heartbeat-s", "0",
                "--metrics-out", str(metrics), "--log-json", str(events),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            deadline = time.monotonic() + 180
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.1)
            assert port_file.exists(), "server never became ready"
            port = int(port_file.read_text().strip())
            base = f"http://127.0.0.1:{port}"
            status, payload, _ = _post(
                base + "/v1/segment?output=mask",
                _phantom_body(),
                _raw_headers(CANVAS, CANVAS),
                timeout=60.0,
            )
            assert status == 200 and payload["mask_pixels"] > 0
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        assert "drained and stopped" in out
        # the flushed artifacts pass the schema gate, with the serving
        # series asserted through the new --expect-* hooks
        res = run_checker(
            "--events", events, "--metrics", metrics,
            "--expect-counter", "serving_requests_total=1",
            "--expect-histogram", "serving_request_seconds=1",
            "--expect-histogram", "serving_queue_wait_seconds=1",
        )
        assert res.returncode == 0, res.stderr


# -- loadgen ---------------------------------------------------------------


class TestLoadgen:
    def test_percentiles(self):
        from nm03_capstone_project_tpu.serving.loadgen import _percentile

        vals = sorted(float(i) for i in range(1, 101))
        assert _percentile(vals, 50) == 50.0
        assert _percentile(vals, 99) == 99.0
        assert _percentile([], 50) == 0.0

    def test_loadgen_against_live_server(self, served, tmp_path):
        """The acceptance loop: loadgen drives the loopback server, the
        summary shows coalescing, and the results JSON lands on disk."""
        from nm03_capstone_project_tpu.serving.loadgen import (
            _make_payloads,
            run_load,
        )

        app, base = served
        payloads = _make_payloads(CANVAS, CANVAS, n_distinct=2, dicom=False)
        summary = run_load(
            base + "/v1/segment?output=mask",
            payloads,
            n_requests=16,
            concurrency=8,
            rate_rps=0.0,
            timeout_s=30.0,
        )
        assert summary["requests_ok"] == 16
        assert summary["max_observed_batch"] > 1
        assert summary["latency_ms"]["p99"] >= summary["latency_ms"]["p50"] > 0
        assert summary["throughput_rps"] > 0

    def test_self_serve_smoke_cli(self, tmp_path):
        """The tier-1-safe smoke the docs advertise: nm03-loadgen
        --self-serve on CPU, small N, one warm bucket."""
        results = tmp_path / "loadgen.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.loadgen",
                "--self-serve",
                "--self-serve-args",
                f"--canvas {CANVAS} --buckets 2 --lanes 1 --max-wait-ms 20",
                "--requests", "8", "--concurrency", "4", "--warmup", "1",
                "--height", str(CANVAS), "--width", str(CANVAS),
                "--results-json", str(results),
            ],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        summary = json.loads(results.read_text())
        assert summary["requests_ok"] == 8
        assert summary["server_status"]["draining"] is True


# -- in-memory JPEG encoding ------------------------------------------------


class TestEncodeJpegBytes:
    def test_magic_and_roundtrip(self):
        from nm03_capstone_project_tpu.render.export import encode_jpeg_bytes

        img = (np.arange(64 * 64, dtype=np.uint32) % 256).astype(np.uint8)
        img = img.reshape(64, 64)
        blob = encode_jpeg_bytes(img)
        assert blob[:2] == b"\xff\xd8" and blob[-2:] == b"\xff\xd9"
        PIL = pytest.importorskip("PIL.Image")
        import io

        back = np.asarray(PIL.open(io.BytesIO(blob)))
        assert back.shape == (64, 64)

    def test_rejects_non_uint8(self):
        from nm03_capstone_project_tpu.render.export import encode_jpeg_bytes

        with pytest.raises(ValueError, match="uint8"):
            encode_jpeg_bytes(np.zeros((8, 8), np.float32))
