"""Golden-image regression over the 5 exported pipeline stages.

Formalizes the reference's golden-eyeball contract: its test driver exports
five stage JPEGs for a human to inspect (src/test/test_pipeline.cpp:162-179),
which means any renderer or pipeline regression is invisible to an automated
run. Here the same five renders for fixed phantom slices are committed as
arrays (tests/golden/*.npz, produced by tests/golden/make_goldens.py) and
pinned: a change to windowing, letterboxing, overlay opacity, border banding,
or any pipeline stage shifts pixels and fails loudly.

Tolerance: renders are uint8; tiny float drift across jax/XLA versions may
move a value by a count or two at gradient pixels, so we allow per-pixel
|diff| <= 3 and mean |diff| <= 0.1 — a real regression (different window,
shifted letterbox, changed opacity) moves whole regions by tens of counts.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"
_spec = importlib.util.spec_from_file_location(
    "make_goldens", GOLDEN_DIR / "make_goldens.py"
)
make_goldens = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(make_goldens)
SEEDS, compute_renders = make_goldens.SEEDS, make_goldens.compute_renders
STAGE_NAMES = (
    "original_image",
    "preprocessed_image",
    "segmentation",
    "erosion_result",
    "final_dilated_result",
)


@pytest.mark.parametrize("seed", SEEDS)
class TestGoldenStages:
    @pytest.mark.slow
    def test_stage_renders_match_goldens(self, seed):
        path = GOLDEN_DIR / f"stage_renders_seed{seed}.npz"
        golden = np.load(path)
        assert set(golden.files) == set(STAGE_NAMES)
        got = compute_renders(seed)
        for name in STAGE_NAMES:
            want = golden[name]
            have = got[name]
            assert have.shape == want.shape and have.dtype == want.dtype, name
            diff = np.abs(have.astype(np.int16) - want.astype(np.int16))
            assert diff.max() <= 3, (
                f"{name} seed {seed}: max pixel diff {diff.max()} "
                f"at {np.unravel_index(diff.argmax(), diff.shape)}"
            )
            assert diff.mean() <= 0.1, (
                f"{name} seed {seed}: mean pixel diff {diff.mean():.3f}"
            )

    def test_goldens_are_nontrivial(self, seed):
        # a golden of zeros would pass any diff test; require every stage to
        # carry real signal (the phantom lesion is segmented and rendered)
        golden = np.load(GOLDEN_DIR / f"stage_renders_seed{seed}.npz")
        for name in STAGE_NAMES:
            assert golden[name].sum() > 0, f"{name} golden is blank"
        # the dilated mask strictly contains the segmentation's fill area
        seg = (golden["segmentation"] > 0).sum()
        dil = (golden["final_dilated_result"] > 0).sum()
        assert dil > seg > 0
