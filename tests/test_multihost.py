"""Multi-host distributed backend, exercised with REAL separate processes.

The reference is strictly single-process shared memory + OpenMP (SURVEY.md
section 2.3). This framework's distributed backend is ``jax.distributed``
over XLA collectives; these tests validate it the way a pod would use it —
two OS processes, each owning 4 virtual CPU devices, joined through a
coordinator into one 8-device job — rather than only asserting the
single-process no-op. Each worker runs the framework's own entry points
(``distributed.initialize`` with explicit args, ``distributed.global_mesh``,
``process_batch_sharded``) and the parent asserts both workers saw the
global device set and produced the single-device-identical result.
"""

import socket
import subprocess
import sys
import textwrap
from pathlib import Path
import pytest


pytestmark = [pytest.mark.slow, pytest.mark.multiproc]


_REPO = Path(__file__).parents[1]

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from nm03_capstone_project_tpu.parallel import distributed
    joined = distributed.initialize(
        coordinator_address=f"127.0.0.1:{{port}}",
        num_processes=nproc,
        process_id=pid,
    )
    assert joined, "explicit multi-process initialize must join"
    info = distributed.process_info()
    assert info["process_count"] == nproc, info
    assert info["global_devices"] == 4 * nproc, info

    import numpy as np
    import jax.numpy as jnp
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.data.synthetic import phantom_slice
    from nm03_capstone_project_tpu.parallel import process_batch_sharded
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig(grow_block_iters=8, grow_max_iters=256)
    b = info["global_devices"]
    pixels = np.stack(
        [phantom_slice(64, 64, seed=i, lesion_radius=0.14) for i in range(b)]
    ).astype(np.float32)
    dims = np.full((b, 2), 64, np.int32)

    mesh = distributed.global_mesh(("data",))
    assert mesh.size == 4 * nproc
    out = process_batch_sharded(jnp.asarray(pixels), jnp.asarray(dims), cfg, mesh)
    # allgather the full global mask (shards live on BOTH processes) and
    # require voxel-exact equality with the local unsharded reference — a
    # popcount-preserving sharding bug must not pass
    from jax.experimental import multihost_utils

    got = np.asarray(multihost_utils.process_allgather(out["mask"], tiled=True))
    want = np.asarray(process_batch(pixels, dims, cfg)["mask"])
    assert got.shape == want.shape and (got == want).all()
    total = int(got.sum())
    assert total > 0
    print(f"MHOK {{pid}} {{total}}", flush=True)

    # z-sharded volume across BOTH processes: the ppermute halo exchange and
    # psum convergence cross the process boundary (the DCN-riding pattern)
    from nm03_capstone_project_tpu.data.synthetic import phantom_volume
    from nm03_capstone_project_tpu.parallel import process_volume_zsharded
    from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

    meshz = distributed.global_mesh(("z",))
    vol = phantom_volume(n_slices=2 * mesh.size, height=64, width=64, seed=0)
    vdims = jnp.asarray([64, 64], jnp.int32)
    vout = process_volume_zsharded(jnp.asarray(vol), vdims, cfg, meshz)
    zgot = np.asarray(multihost_utils.process_allgather(vout["mask"], tiled=True))
    zwant = np.asarray(process_volume(jnp.asarray(vol), vdims, cfg)["mask"])
    assert zgot.shape == zwant.shape and (zgot == zwant).all()
    ztotal = int(zgot.sum())
    assert ztotal > 0
    print(f"ZSOK {{pid}} {{ztotal}}", flush=True)
    """
).format(repo=str(_REPO))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_job(script, tmp_path, nproc, port, attempt, extra_args=()):
    """Spawn the nproc workers; (rcs, outs, errs) once all exit or time out."""
    # output to FILES, not pipes: pipe backpressure between two workers
    # blocked in a collective would deadlock a sequential communicate()
    logs = [
        (
            open(tmp_path / f"a{attempt}_w{pid}.out", "w+"),
            open(tmp_path / f"a{attempt}_w{pid}.err", "w+"),
        )
        for pid in range(nproc)
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(nproc), str(port), *extra_args],
            stdout=logs[pid][0],
            stderr=logs[pid][1],
            text=True,
        )
        for pid in range(nproc)
    ]
    rcs, outs, errs = [], [], []
    try:
        for pid, p in enumerate(procs):
            try:
                rcs.append(p.wait(timeout=600))
            except subprocess.TimeoutExpired:
                rcs.append(None)
            outs.append((tmp_path / f"a{attempt}_w{pid}.out").read_text())
            errs.append((tmp_path / f"a{attempt}_w{pid}.err").read_text())
    finally:
        for p in procs:  # a failed/odd sibling must not outlive the test
            if p.poll() is None:
                p.kill()
                p.wait()
        for fo, fe in logs:
            fo.close()
            fe.close()
    return rcs, outs, errs


def run_job_with_port_retry(script, tmp_path, nproc, extra_args=(), attempts=3):
    """Run an nproc job, retrying with a fresh port on coordinator bind loss.

    _free_port closes the socket before the coordinator binds it, so a
    concurrent process can steal the port in between; a bind failure detected
    on worker 0 is retried instead of flaking the test. Asserts all workers
    exit 0 and returns their stdouts.
    """
    outs = []
    for attempt in range(attempts):
        port = _free_port()
        rcs, outs, errs = _run_job(
            script, tmp_path, nproc, port, attempt, extra_args=extra_args
        )
        err0 = errs[0].lower()
        bind_lost = rcs[0] not in (0, None) and (
            "address already in use" in err0
            or "failed to bind" in err0
            or "bind failed" in err0
        )
        if bind_lost and attempt < attempts - 1:
            continue
        for pid in range(nproc):
            assert rcs[pid] == 0, f"worker {pid} rc={rcs[pid]}:\n{errs[pid][-2000:]}"
        break
    return outs


class TestMultiProcess:
    def test_two_process_job_runs_sharded_pipeline(self, tmp_path):
        script = tmp_path / "mh_worker.py"
        script.write_text(_WORKER)
        nproc = 2
        outs = run_job_with_port_retry(script, tmp_path, nproc)
        for marker in ("MHOK", "ZSOK"):
            sums = set()
            for pid, out in enumerate(outs):
                lines = [l for l in out.splitlines() if l.startswith(marker)]
                assert lines, f"worker {pid} missing {marker} line: {out!r}"
                _, got_pid, total = lines[0].split()
                assert int(got_pid) == pid
                sums.add(int(total))
            # both processes converged on the same correct, nonzero result
            assert len(sums) == 1 and sums.pop() > 0, marker
