import numpy as np

from nm03_capstone_project_tpu.render.export import export_pairs, save_jpeg
from nm03_capstone_project_tpu.render.render import (
    render_gray,
    render_overlay,
    render_segmentation,
)


def test_matmul_and_gather_samplers_agree(monkeypatch):
    """The TPU (MXU matmul) and CPU (gather) resample paths must agree.

    Masks (nearest, one-hot) must be EXACT; grayscale (bilinear) may differ
    by one 8-bit count at isolated pixels from lerp reassociation.
    """
    from nm03_capstone_project_tpu.render import render as rr

    rng = np.random.default_rng(7)
    px = np.zeros((128, 128), np.float32)
    px[:100, :80] = rng.random((100, 80)).astype(np.float32) * 900
    mask = np.zeros((128, 128), np.uint8)
    mask[20:60, 10:50] = 1
    dims = np.asarray([100, 80], np.int32)

    monkeypatch.setattr(rr, "_mxu_backend", lambda: False)  # force gather
    gather_gray = np.asarray(render_gray(px, dims, 256))
    gather_seg = np.asarray(render_segmentation(mask, dims, 256))
    monkeypatch.setattr(rr, "_mxu_backend", lambda: True)
    matmul_gray = np.asarray(render_gray(px, dims, 256))
    matmul_seg = np.asarray(render_segmentation(mask, dims, 256))

    np.testing.assert_array_equal(matmul_seg, gather_seg)
    diff = np.abs(matmul_gray.astype(np.int16) - gather_gray.astype(np.int16))
    assert diff.max() <= 1, f"max bilinear path divergence {diff.max()}"


def test_render_gray_letterbox_geometry():
    # wide slice: 100x200 -> scaled to 256x128 region centered vertically
    img = np.full((100, 200), 500.0, np.float32)
    img[0, 0] = 0.0  # establish a window
    canvas = np.zeros((256, 256), np.float32)
    canvas[:100, :200] = img
    dims = np.asarray([100, 200], np.int32)
    out = np.asarray(render_gray(canvas, dims, 256))
    assert out.shape == (256, 256)
    assert out[:60, :].max() == 0  # top letterbox band is black
    assert out[196:, :].max() == 0  # bottom band
    assert out[128, 128] > 200  # center is bright (value 500 in window [0,500])


def test_render_gray_constant_image_no_nan():
    canvas = np.full((64, 64), 7.0, np.float32)
    dims = np.asarray([64, 64], np.int32)
    out = np.asarray(render_gray(canvas, dims, 64))
    assert out.min() >= 0 and out.max() <= 255


def test_render_segmentation_opacity_and_border():
    mask = np.zeros((64, 64), np.uint8)
    mask[16:48, 16:48] = 1
    dims = np.asarray([64, 64], np.int32)
    out = np.asarray(render_segmentation(mask, dims, 64, 0.6, 1.0, 2))
    # interior at fill opacity, border at full opacity, outside black
    assert out[32, 32] == 153  # 0.6 * 255
    assert out[16, 32] == 255  # border band
    assert out[8, 8] == 0


def test_render_segmentation_scales_to_output():
    mask = np.zeros((32, 32), np.uint8)
    mask[8:24, 8:24] = 1
    dims = np.asarray([32, 32], np.int32)
    out = np.asarray(render_segmentation(mask, dims, 128, 0.6, 1.0, 2))
    assert out[64, 64] > 0
    ys, xs = np.nonzero(out)
    # the 16px square maps to ~64px in render space
    assert 30 <= ys.min() <= 34 and 94 <= ys.max() <= 98


def test_render_overlay_composites():
    canvas = np.full((64, 64), 100.0, np.float32)
    canvas[0, 0] = 0.0
    canvas[0, 1] = 200.0  # window [0, 200] -> background gray ~127
    mask = np.zeros((64, 64), np.uint8)
    mask[20:40, 20:40] = 1
    dims = np.asarray([64, 64], np.int32)
    out = np.asarray(render_overlay(canvas, mask, dims, 64))
    assert out[30, 30] > out[10, 10] + 50  # white overlay lifts the lesion


class TestFusedRenderPair:
    """render_pair_fused vs the two independent renders: pixel-identical
    on both legs, on both sampler paths (ISSUE 2 tentpole)."""

    def _case(self, canvas, th, tw, seed=3):
        rng = np.random.default_rng(seed)
        px = np.zeros((canvas, canvas), np.float32)
        px[:th, :tw] = rng.random((th, tw)).astype(np.float32) * 900
        mask = np.zeros((canvas, canvas), np.uint8)
        mask[:th, :tw] = (rng.random((th, tw)) < 0.35).astype(np.uint8)
        dims = np.asarray([th, tw], np.int32)
        return px, mask, dims

    def _assert_identical(self, px, mask, dims, render_size=128):
        import dataclasses

        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.render.render import render_pair

        cfg = PipelineConfig(render_size=render_size)
        cfg_unfused = dataclasses.replace(cfg, render_fused=False)
        g1, s1 = render_pair(px, mask, dims, cfg)
        g2, s2 = render_pair(px, mask, dims, cfg_unfused)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))

    def test_pixel_identical_gather_path(self, monkeypatch):
        from nm03_capstone_project_tpu.render import render as rr

        monkeypatch.setattr(rr, "_mxu_backend", lambda: False)
        for canvas, th, tw in ((128, 100, 80), (128, 128, 128), (64, 33, 64)):
            self._assert_identical(*self._case(canvas, th, tw))

    def test_pixel_identical_matmul_path(self, monkeypatch):
        from nm03_capstone_project_tpu.render import render as rr

        monkeypatch.setattr(rr, "_mxu_backend", lambda: True)
        for canvas, th, tw in ((128, 100, 80), (64, 64, 30)):
            self._assert_identical(*self._case(canvas, th, tw))

    def test_pixel_identical_under_vmap(self):
        import jax

        from nm03_capstone_project_tpu.config import PipelineConfig
        import dataclasses

        from nm03_capstone_project_tpu.render.render import render_pair

        rng = np.random.default_rng(5)
        px = rng.random((4, 64, 64)).astype(np.float32) * 500
        mask = (rng.random((4, 64, 64)) < 0.3).astype(np.uint8)
        dims = np.asarray([[64, 64], [50, 40], [64, 20], [10, 64]], np.int32)
        cfg = PipelineConfig(render_size=96)
        cfg_u = dataclasses.replace(cfg, render_fused=False)
        f = jax.jit(jax.vmap(lambda p, m, d: render_pair(p, m, d, cfg)))
        fu = jax.jit(jax.vmap(lambda p, m, d: render_pair(p, m, d, cfg_u)))
        for a, b in zip(f(px, mask, dims), fu(px, mask, dims)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_opacity_u8_matches_device_math(self):
        # the fused integer leg's precomputed levels vs the f32 alpha path
        # for awkward opacities (0.6 is the classic: f32(0.6)*255 crosses
        # 153 only because the f32 product rounds UP)
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.render.render import _opacity_u8

        for op in (0.0, 0.1, 0.25, 0.6, 0.47, 0.999, 1.0):
            dev = int(
                np.asarray(
                    jnp.clip(
                        jnp.float32(op) * 255.0, 0, 255
                    ).astype(jnp.uint8)
                )
            )
            assert _opacity_u8(op) == dev, op


def test_save_jpeg_and_export_pairs(tmp_path):
    img = np.zeros((32, 32), np.uint8)
    save_jpeg(img, tmp_path / "a.jpg")
    assert (tmp_path / "a.jpg").stat().st_size > 0
    done = export_pairs(
        [("s1", img, img), ("s2", img, img)], tmp_path / "pairs"
    )
    assert done == ["s1", "s2"]
    names = sorted(p.name for p in (tmp_path / "pairs").iterdir())
    assert names == [
        "s1_original.jpg",
        "s1_processed.jpg",
        "s2_original.jpg",
        "s2_processed.jpg",
    ]
