"""Sharded serving fleet tests (ISSUE 6): lanes, fan-out, per-lane state.

Three layers, mirroring tests/test_serving.py's structure:

* batcher fan-out against a lane-aware fake executor (no jax): chunking
  policy, lane assignment, per-lane accounting;
* the real ``WarmExecutor`` on the conftest's 8 virtual CPU devices:
  per-lane warm executables, lane state, cross-lane mask equality;
* end-to-end: an in-process multi-lane server under concurrent traffic,
  and the acceptance subprocess — ``nm03-serve`` on a forced 8-device
  host (mirroring tests/test_multihost.py's env discipline) serving
  batches across all lanes, masks bit-identical to single-device, gated
  by ``check_telemetry.py --expect-gauge serving_lanes_ready=8``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_slice
from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue, ServeRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


def _reqs(n, hw=16):
    return [
        ServeRequest(
            request_id=f"r{i}",
            pixels=np.ones((hw, hw), np.float32),
            dims=(hw, hw),
        )
        for i in range(n)
    ]


class FakeLaneExecutor:
    """Lane-aware executor stand-in recording (batch shape, lane) pairs."""

    def __init__(self, buckets=(1, 2, 4), lanes=4, canvas=16, min_dim=4):
        self.cfg = SimpleNamespace(canvas=canvas, min_dim=min_dim)
        self.buckets = tuple(buckets)
        self.lane_count = lanes
        self.calls = []
        self._lock = threading.Lock()

    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run_batch(self, pixels, dims, lane=0):
        with self._lock:
            self.calls.append((pixels.shape[0], lane))
        mask = (pixels > 0).astype(np.uint8)
        return mask, np.ones(pixels.shape[0], bool)


class TestBatcherFanOut:
    def test_window_splits_across_lanes(self):
        ex = FakeLaneExecutor(buckets=(1, 2, 4), lanes=4)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        reqs = _reqs(12)
        b.execute(reqs)
        # 12 over 4 lanes -> chunk target 3 -> bucket 4 -> 3 chunks
        assert sorted(c[0] for c in ex.calls) == [4, 4, 4]
        assert sorted(c[1] for c in ex.calls) == [0, 1, 2]
        for r in reqs:
            assert r.done.is_set() and r.error is None
            assert r.mask.shape == r.dims and r.batch_size == 4

    def test_effective_max_batch_is_fleet_capacity(self):
        ex = FakeLaneExecutor(buckets=(1, 2, 4), lanes=4)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        assert b.effective_max_batch() == 16
        b2 = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0, max_batch=6)
        assert b2.effective_max_batch() == 6

    def test_explicit_max_batch_validated_against_fleet(self):
        ex = FakeLaneExecutor(buckets=(1, 2), lanes=4)
        DynamicBatcher(AdmissionQueue(8), ex, max_batch=8)  # 4 x 2: fits
        with pytest.raises(ValueError, match="fleet capacity"):
            DynamicBatcher(AdmissionQueue(8), ex, max_batch=9)

    def test_unresolved_lanes_validate_at_start(self):
        # the normal server path: lanes resolve during warmup, AFTER the
        # batcher is constructed — an over-capacity max_batch must still
        # fail fast at start(), not silently clamp (PR-4 contract)
        ex = FakeLaneExecutor(buckets=(1, 2), lanes=None)
        b = DynamicBatcher(AdmissionQueue(8), ex, max_batch=9)  # unknown yet
        ex.lane_count = 2  # "warmup" resolved 2 lanes: capacity 4
        with pytest.raises(ValueError, match="fleet capacity"):
            b.start()

    def test_single_request_stays_on_one_lane(self):
        ex = FakeLaneExecutor(lanes=4)
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        b.execute(_reqs(1))
        assert ex.calls == [(1, 0)]

    def test_per_lane_stats(self):
        ex = FakeLaneExecutor(buckets=(1, 2), lanes=2)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        b.execute(_reqs(4))  # 2 chunks of bucket 2 on lanes 0 and 1
        st = b.stats()
        assert st["batches"] == 2 and st["requests"] == 4
        assert st["lane_batches"] == {"0": 1, "1": 1}

    def test_chunk_failure_contained_to_its_riders(self):
        class FailLane1(FakeLaneExecutor):
            def run_batch(self, pixels, dims, lane=0):
                if lane == 1:
                    raise RuntimeError("lane 1 boom")
                return super().run_batch(pixels, dims, lane)

        ex = FailLane1(buckets=(1, 2), lanes=2)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        reqs = _reqs(4)
        b.execute(reqs)
        ok = [r for r in reqs if r.error is None]
        failed = [r for r in reqs if r.error is not None]
        assert len(ok) == 2 and len(failed) == 2
        assert all(isinstance(r.error, RuntimeError) for r in failed)
        assert all(r.done.is_set() for r in reqs)


CFG = PipelineConfig(canvas=CANVAS)


class TestWarmExecutorLanes:
    def test_warmup_per_lane_and_cross_lane_equality(self):
        from nm03_capstone_project_tpu.serving.executor import WarmExecutor

        ex = WarmExecutor(CFG, buckets=(1,), lanes=2)
        assert ex.lane_count == 2  # requested, pre-resolution
        timings = ex.warmup()
        assert set(timings) == {"lane0", "lane1"}
        assert ex.warm and ex.lanes_ready == 2
        state = ex.lane_state()
        assert [s["lane"] for s in state] == [0, 1]
        assert all(s["warm"] for s in state)
        img = phantom_slice(CANVAS, CANVAS, seed=2).astype(np.float32)
        px = img[None]
        dm = np.asarray([[CANVAS, CANVAS]], np.int32)
        m0, c0 = ex.run_batch(px, dm, lane=0)
        m1, c1 = ex.run_batch(px, dm, lane=1)
        np.testing.assert_array_equal(m0, m1)
        assert [s["batches"] for s in ex.lane_state()] == [1, 1]
        with pytest.raises(ValueError, match="lane"):
            ex.run_batch(px, dm, lane=7)

    def test_lane_overflow_rejected(self):
        from nm03_capstone_project_tpu.serving.executor import WarmExecutor

        with pytest.raises(ValueError, match="lanes"):
            WarmExecutor(CFG, buckets=(1,), lanes=0)


def _post(url, body, headers, timeout=60.0):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _raw_headers(h, w):
    return {
        "Content-Type": "application/octet-stream",
        "X-Nm03-Height": str(h),
        "X-Nm03-Width": str(w),
    }


def _expected_mask_pixels(img: np.ndarray) -> int:
    """Single-device reference through the same hub program the fleet runs."""
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

    out = process_slice(
        jnp.asarray(img.astype(np.float32)),
        jnp.asarray([img.shape[0], img.shape[1]], jnp.int32),
        CFG,
    )
    return int(np.count_nonzero(np.asarray(out["mask"])))


class TestMultiLaneServingE2E:
    def test_concurrent_traffic_fans_across_lanes_mask_identical(self):
        from nm03_capstone_project_tpu.serving.server import ServingApp

        app = ServingApp(
            cfg=CFG,
            queue_capacity=64,
            buckets=(1, 2),
            max_wait_s=0.05,
            request_timeout_s=60.0,
            lanes=4,
        )
        app.start()
        try:
            imgs = {s: phantom_slice(CANVAS, CANVAS, seed=s) for s in (0, 1, 2)}
            want = {s: _expected_mask_pixels(imgs[s]) for s in imgs}
            results = []
            lock = threading.Lock()

            def one(i):
                p = app.segment(imgs[i % 3], render=False)
                with lock:
                    results.append((i % 3, p))

            threads = [
                threading.Thread(target=one, args=(i,)) for i in range(16)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 16
            # masks bit-identical to the single-device pipeline, whatever
            # lane served them
            for seed, payload in results:
                assert payload["mask_pixels"] == want[seed], seed
            st = app.status()
            assert st["lanes"]["count"] == 4 and st["lanes"]["ready"] == 4
            assert st["mesh_shape"] == [4]
            lanes_used = {
                s["lane"] for s in st["lanes"]["per_lane"] if s["batches"] > 0
            }
            assert len(lanes_used) >= 2, st["lanes"]
            assert app.registry.get("serving_lanes_ready").value == 4
            hub = st["compile_hub"]
            assert hub["executables"] >= 8  # 4 lanes x 2 buckets
        finally:
            app.begin_drain(reason="test")
            app.close()


class TestServeCliAcceptance:
    @pytest.mark.slow
    def test_eight_lane_subprocess_serves_all_lanes(self, tmp_path):
        """The ISSUE 6 acceptance bar, end to end in a real process:
        ``nm03-serve`` on 8 forced virtual CPU devices serves concurrent
        batches across all lanes (observed via serving_lane_* metrics and
        gated by --expect-gauge serving_lanes_ready=8) with masks
        bit-identical to the single-device pipeline.
        """
        port_file = tmp_path / "port"
        metrics = tmp_path / "metrics.json"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1", "--lanes", "0",
                "--max-wait-ms", "30", "--heartbeat-s", "0",
                "--metrics-out", str(metrics),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            deadline = time.monotonic() + 300
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.2)
            assert port_file.exists(), "server never became ready"
            base = f"http://127.0.0.1:{int(port_file.read_text())}"
            img = phantom_slice(CANVAS, CANVAS, seed=1)
            want = _expected_mask_pixels(img)
            body = img.astype("<f4").tobytes()
            results = []
            lock = threading.Lock()

            def one():
                s, p = _post(
                    base + "/v1/segment?output=mask",
                    body,
                    _raw_headers(CANVAS, CANVAS),
                )
                with lock:
                    results.append((s, p))

            threads = [threading.Thread(target=one) for _ in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 16
            assert all(s == 200 for s, _ in results), results
            assert all(p["mask_pixels"] == want for _, p in results)
            with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
                st = json.loads(r.read())
            assert st["lanes"]["count"] == 8 and st["lanes"]["ready"] == 8
            lanes_used = {
                s["lane"] for s in st["lanes"]["per_lane"] if s["batches"] > 0
            }
            # 16 one-slice requests, bucket 1: the window splits 16 ways,
            # wrapping all 8 lanes
            assert len(lanes_used) >= 4, st["lanes"]
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        res = subprocess.run(
            [
                sys.executable, CHECKER,
                "--metrics", str(metrics),
                "--expect-gauge", "serving_lanes_ready=8",
                "--expect-counter", "serving_lane_batches_total=8",
                "--expect-counter", "serving_requests_total=16",
            ],
            capture_output=True, text=True, timeout=60,
        )
        assert res.returncode == 0, res.stderr
