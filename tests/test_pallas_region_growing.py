"""Pallas region-growing kernel vs the portable XLA oracle (interpret mode).

The VMEM-resident fixpoint must be bit-identical to
:func:`ops.region_growing.region_grow` — same band semantics, same
block-amortized convergence, same max_iters cap — so the whole 2D
segmentation suite transfers to the TPU path by this equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.data.synthetic import phantom_slice
from nm03_capstone_project_tpu.ops.elementwise import clip_intensity, normalize
from nm03_capstone_project_tpu.ops.pallas_region_growing import (
    grow_dispatch,
    region_grow_pallas,
)
from nm03_capstone_project_tpu.ops.region_growing import region_grow
from nm03_capstone_project_tpu.ops.seeds import seed_mask


def _case(n=3, hw=64):
    px = np.stack([phantom_slice(hw, hw, seed=i) for i in range(n)]).astype(
        np.float32
    )
    x = clip_intensity(normalize(jnp.asarray(px)))
    dims = jnp.full((n, 2), hw, jnp.int32)
    seeds = jax.vmap(lambda d: seed_mask(d, (hw, hw)))(dims)
    valid = jax.vmap(lambda d: valid_mask(d, (hw, hw)))(dims)
    return x, seeds, valid


class TestPallasGrowInterpret:
    @pytest.mark.parametrize("connectivity", [4, 8])
    def test_matches_xla_oracle(self, connectivity):
        x, seeds, valid = _case()
        kw = dict(
            valid=valid, connectivity=connectivity, block_iters=8, max_iters=256
        )
        want = np.asarray(region_grow(x, seeds, **kw)[0])
        got = np.asarray(region_grow_pallas(x, seeds, **kw, interpret=True)[0])
        assert want.sum() > 0
        np.testing.assert_array_equal(got, want)

    def test_matches_under_vmap(self):
        # the pipeline calls the kernel per-slice under vmap; the pallas
        # batching rule must agree with the direct batched call
        x, seeds, valid = _case()
        got = np.asarray(
            jax.vmap(
                lambda xi, si, vi: region_grow_pallas(
                    xi, si, valid=vi, block_iters=8, max_iters=256, interpret=True
                )[0]
            )(x, seeds, valid)
        )
        want = np.asarray(
            region_grow(x, seeds, valid=valid, block_iters=8, max_iters=256)[0]
        )
        np.testing.assert_array_equal(got, want)

    def test_band_without_seeds_stays_empty(self):
        x, _, valid = _case(n=1)
        seeds = jnp.zeros_like(x, bool)
        got = np.asarray(
            region_grow_pallas(
                x, seeds, valid=valid, block_iters=8, max_iters=64, interpret=True
            )[0]
        )
        assert got.sum() == 0

    def test_max_iters_caps_growth(self):
        # a full-band image with one center seed grows one ring per step;
        # capping iters must freeze the frontier identically in both paths
        hw = 32
        x = jnp.full((hw, hw), 0.8, jnp.float32)
        seeds = jnp.zeros((hw, hw), bool).at[hw // 2, hw // 2].set(True)
        kw = dict(block_iters=4, max_iters=8)
        want = np.asarray(region_grow(x, seeds, **kw)[0])
        got = np.asarray(region_grow_pallas(x, seeds, **kw, interpret=True)[0])
        assert 0 < want.sum() < hw * hw
        np.testing.assert_array_equal(got, want)

    def test_rejects_bad_connectivity(self):
        x, seeds, _ = _case(n=1)
        with pytest.raises(ValueError, match="connectivity"):
            region_grow_pallas(x, seeds, connectivity=6)


class TestDispatch:
    def test_cpu_dispatch_uses_xla_path(self):
        x, seeds, valid = _case(n=2)
        a = np.asarray(
            grow_dispatch(
                x, seeds, 0.74, 0.91, valid=valid, block_iters=8, max_iters=256,
                use_pallas=True,  # degrades to XLA off-TPU
            )[0]
        )
        b = np.asarray(
            region_grow(x, seeds, valid=valid, block_iters=8, max_iters=256)[0]
        )
        np.testing.assert_array_equal(a, b)


def test_oversized_slice_falls_back_to_xla():
    # the whole-slice fixpoint needs ~5 slice-sized VMEM buffers; past the
    # budget the wrapper must produce the XLA result, not a Mosaic
    # compile-time OOM (the 1024^2 regression)
    import numpy as np
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.ops.pallas_region_growing import (
        region_grow_pallas,
    )
    from nm03_capstone_project_tpu.ops.region_growing import region_grow

    rng = np.random.default_rng(2)
    img = jnp.asarray((rng.random((1024, 1024)) * 0.5 + 0.4).astype(np.float32))
    seeds = jnp.zeros((1024, 1024), bool).at[512, 512].set(True)
    got = np.asarray(region_grow_pallas(img, seeds, 0.74, 0.91)[0])
    want = np.asarray(region_grow(img, seeds, 0.74, 0.91)[0])
    np.testing.assert_array_equal(got, want)


class TestPallasConvergedFlag:
    """VERDICT r4 item 4 on the Pallas path: the kernel's SMEM flag must
    agree with the XLA oracle's in both regimes (interpret mode)."""

    def _setup(self):
        img = np.full((32, 32), 0.8, np.float32)
        seeds = np.zeros((32, 32), bool)
        seeds[0, 0] = True
        return img, seeds

    @pytest.mark.parametrize("block_iters,max_iters", [(4, 8), (16, 256)])
    def test_flag_matches_xla(self, block_iters, max_iters):
        img, seeds = self._setup()
        kw = dict(block_iters=block_iters, max_iters=max_iters)
        want_mask, want_conv = region_grow(img, seeds, **kw)
        got_mask, got_conv = region_grow_pallas(img, seeds, **kw, interpret=True)
        np.testing.assert_array_equal(np.asarray(got_mask), np.asarray(want_mask))
        assert bool(got_conv) == bool(want_conv)
        assert bool(want_conv) == (max_iters >= 64)  # capped vs full regime

    def test_batched_flag_reduces_like_xla(self):
        # XLA's batched loop couples lanes through one global popcount, so
        # its flag is a scalar; the Pallas wrapper reduces per-slice flags
        # with all() to match that contract
        img, seeds = self._setup()
        imgs = np.stack([img, np.full((32, 32), 0.1, np.float32)])
        seedss = np.stack([seeds, seeds])
        _, conv = region_grow_pallas(
            imgs, seedss, block_iters=4, max_iters=8, interpret=True
        )
        assert np.asarray(conv).shape == ()
        assert not bool(conv)  # lane 0 capped
