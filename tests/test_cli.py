"""End-to-end CLI/runner tests on a tiny synthetic cohort.

Formalizes the reference's manual testing (SURVEY.md section 4): the
parallel==sequential output invariant, per-slice fault containment with
success counting, and the resume manifest this framework adds.
"""

import hashlib
from pathlib import Path

import numpy as np
import pytest

from nm03_capstone_project_tpu.cli.runner import CohortProcessor
from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

CFG = PipelineConfig(canvas=128, render_size=128)
BCFG = BatchConfig(batch_size=3, io_workers=2)


@pytest.fixture(scope="module")
def cohort(tmp_path_factory):
    root = tmp_path_factory.mktemp("cohort")
    write_synthetic_cohort(root, n_patients=2, n_slices=4, height=128, width=120)
    return root


def digest_tree(root) -> str:
    h = hashlib.sha256()
    for p in sorted(Path(root).rglob("*.jpg")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def test_sequential_run(cohort, tmp_path):
    proc = CohortProcessor(cohort, tmp_path / "seq", cfg=CFG, mode="sequential")
    summary = proc.process_all_patients()
    assert summary.patients_ok == 2
    assert summary.succeeded_slices == 8
    jpgs = list((tmp_path / "seq").rglob("*.jpg"))
    assert len(jpgs) == 16  # 2 per slice
    assert (tmp_path / "seq" / "manifest.json").exists()


def test_parallel_equals_sequential(cohort, tmp_path):
    seq = CohortProcessor(cohort, tmp_path / "seq", cfg=CFG, mode="sequential")
    seq.process_all_patients()
    par = CohortProcessor(
        cohort, tmp_path / "par", cfg=CFG, batch_cfg=BCFG, mode="parallel"
    )
    par_summary = par.process_all_patients()
    assert par_summary.succeeded_slices == 8
    assert digest_tree(tmp_path / "seq") == digest_tree(tmp_path / "par")


def test_corrupt_slice_contained(cohort, tmp_path):
    """A corrupt .dcm is skipped and counted; the run continues (reference
    catch-and-continue, main_sequential.cpp:267-271)."""
    bad_root = tmp_path / "cohort2"
    write_synthetic_cohort(bad_root, n_patients=1, n_slices=3, height=128, width=128)
    series = next((bad_root / "PGBM-0001").iterdir())
    (series / "1-02.dcm").write_bytes(b"\x00" * 200)  # corrupt
    proc = CohortProcessor(
        bad_root, tmp_path / "out", cfg=CFG, batch_cfg=BCFG, mode="parallel"
    )
    summary = proc.process_all_patients()
    assert summary.patients_ok == 1  # patient still "succeeds" overall
    p = summary.patients[0]
    assert p.total == 3 and p.succeeded == 2
    assert p.failed_slices == ["1-02"]


def test_undersized_slice_guard(tmp_path):
    root = tmp_path / "c"
    write_synthetic_cohort(root, n_patients=1, n_slices=2, height=64, width=128)
    proc = CohortProcessor(root, tmp_path / "o", cfg=CFG, mode="sequential")
    summary = proc.process_all_patients()
    # 64 < min_dim 100 -> every slice fails the reference's dimension guard
    assert summary.succeeded_slices == 0
    assert summary.patients[0].total == 2


def test_resume_skips_done(cohort, tmp_path):
    out = tmp_path / "res"
    proc = CohortProcessor(cohort, out, cfg=CFG, mode="sequential")
    proc.process_all_patients()
    stamp = {p: p.stat().st_mtime for p in out.rglob("*.jpg")}
    proc2 = CohortProcessor(cohort, out, cfg=CFG, mode="sequential", resume=True)
    summary = proc2.process_all_patients()
    assert summary.succeeded_slices == 8  # counted as done
    for p in out.rglob("*.jpg"):
        assert p.stat().st_mtime == stamp[p]  # nothing rewritten


def test_missing_series_dir_is_patient_failure(tmp_path):
    root = tmp_path / "c"
    (root / "PGBM-0001").mkdir(parents=True)  # patient with no series
    write_synthetic_cohort(root, n_patients=1, n_slices=2, height=128, width=128)
    # write_synthetic_cohort created PGBM-0001 with a series; add empty patient
    (root / "PGBM-0002").mkdir()
    proc = CohortProcessor(root, tmp_path / "o", cfg=CFG, mode="sequential")
    summary = proc.process_all_patients()
    assert summary.patients_ok == 1
    assert len(summary.patients) == 2


def test_cli_arg_round_trip():
    from nm03_capstone_project_tpu.cli.sequential import build_parser

    args = build_parser().parse_args(
        ["--grow-low", "0.5", "--grow-high", "0.8", "--canvas", "128", "--synthetic", "1"]
    )
    from nm03_capstone_project_tpu.cli import common

    cfg = common.pipeline_config_from_args(args)
    assert cfg.grow_low == 0.5 and cfg.grow_high == 0.8 and cfg.canvas == 128
    # defaults match the reference contract
    d = PipelineConfig()
    assert (d.norm_low, d.norm_high) == (0.5, 2.5)
    assert (d.clip_low, d.clip_high) == (0.68, 4000.0)
    assert (d.grow_low, d.grow_high) == (0.74, 0.91)


def test_sequential_parser_accepts_distributed_flags():
    # README advertises --distributed on every batch driver; the sequential
    # parser silently lacked the group (ADVICE r2) so argparse rejected it
    from nm03_capstone_project_tpu.cli.sequential import build_parser

    args = build_parser().parse_args(
        ["--synthetic", "1", "--distributed", "--num-processes", "2",
         "--process-id", "1", "--coordinator-address", "h:1234"]
    )
    assert args.distributed and args.num_processes == 2


def test_allgather_cluster_counts_survives_voxel_scale_counters(monkeypatch):
    # voxel counters (up to 65536 per slice) overflowed the old int32 path
    # past ~33k slices (ADVICE r2). The fix must survive jax's int64->int32
    # canonicalization inside the multi-process collective (x64 is never
    # enabled here), so simulate it: the stub casts whatever it is handed to
    # int32, exactly what device_put does on the >1-process branch.
    from jax.experimental import multihost_utils

    from nm03_capstone_project_tpu.cli import common

    def canonicalizing_allgather(arr):
        squeezed = np.asarray(arr).astype(np.int32)  # would clip/wrap int64
        return np.stack([squeezed, squeezed])  # pretend world=2, equal ranks

    monkeypatch.setattr(
        multihost_utils, "process_allgather", canonicalizing_allgather
    )
    big = 70_000 * 65_536  # ~4.6e9 > 2**31
    out = common.allgather_cluster_counts(
        {"inter": big, "union": big + 1}, world=2
    )
    assert out["inter"] == 2 * big and out["union"] == 2 * (big + 1)
    assert out["per_process"]["1"]["inter"] == big

    with pytest.raises(ValueError, match="non-negative"):
        common.allgather_cluster_counts({"inter": -1}, world=1)


def test_export_failure_not_counted_as_success(cohort, tmp_path, monkeypatch):
    """A slice whose JPEG never hits disk must be FAILED, not DONE."""
    import nm03_capstone_project_tpu.render.export as export_mod

    real = export_mod.save_jpeg

    def flaky(image, path, quality=90):
        if "1-03" in str(path):
            raise IOError("disk full")
        return real(image, path, quality)

    monkeypatch.setattr(export_mod, "save_jpeg", flaky)
    for mode, bcfg in [("sequential", None), ("parallel", BCFG)]:
        out = tmp_path / mode
        proc = CohortProcessor(
            cohort, out, cfg=CFG, batch_cfg=bcfg or BatchConfig(), mode=mode
        )
        summary = proc.process_all_patients()
        assert summary.succeeded_slices == 6, mode  # 1-03 fails in each patient
        for p in summary.patients:
            assert p.failed_slices == ["1-03"], mode
        assert not proc.manifest.is_done("PGBM-0001", "1-03")


def test_manifest_atomicity(tmp_path):
    from nm03_capstone_project_tpu.utils.manifest import Manifest

    m = Manifest(tmp_path)
    m.record("PGBM-0001", "1-01", "done")
    m.flush()
    m2 = Manifest.load_or_create(tmp_path)
    assert m2.is_done("PGBM-0001", "1-01")
    # corrupt manifest falls back to empty rather than crashing
    (tmp_path / "manifest.json").write_text("{not json")
    m3 = Manifest.load_or_create(tmp_path)
    assert m3.data == {}


def test_profile_dir_captures_trace(cohort, tmp_path):
    from nm03_capstone_project_tpu.cli import sequential

    rc = sequential.main(
        [
            "--synthetic", "1", "--synthetic-slices", "2",
            "--canvas", "128", "--render-size", "128",
            "--output", str(tmp_path / "o"),
            "--profile-dir", str(tmp_path / "trace"),
            "--device", "cpu",
        ]
    )
    assert rc == 0
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the dir
    assert any((tmp_path / "trace").rglob("*.xplane.pb"))


def test_show_panel_headless_degrades(monkeypatch, capsys):
    # no display: the viewer must warn and return False, never raise — the
    # reference's MultiViewWindow::run() equivalent is GUI-optional here
    import sys

    import numpy as np

    from nm03_capstone_project_tpu.cli.test_pipeline import show_panel

    # the no-display gate only exists on Linux; pin the platform so this
    # test can't open a real blocking window on a macOS/Windows dev box
    monkeypatch.setattr(sys, "platform", "linux")
    monkeypatch.delenv("DISPLAY", raising=False)
    monkeypatch.delenv("WAYLAND_DISPLAY", raising=False)
    ok = show_panel({"original_image": np.zeros((8, 8), np.uint8)})
    assert ok is False
    assert "--show unavailable" in capsys.readouterr().err


def test_show_panel_draws_five_panes_when_display_present(monkeypatch):
    # with a display, one blocking window shows all 5 stage panes
    # (test_pipeline.cpp:148-158); Agg + stubbed show keeps it headless
    import matplotlib

    matplotlib.use("Agg", force=True)
    import matplotlib.pyplot as plt
    import numpy as np

    from nm03_capstone_project_tpu.cli import test_pipeline

    monkeypatch.setenv("DISPLAY", ":0")
    shown = []
    monkeypatch.setattr(plt, "show", lambda: shown.append(True))
    drawn = {}
    real_subplots = plt.subplots

    def spy_subplots(*a, **k):
        fig, axes = real_subplots(*a, **k)
        drawn["n_axes"] = len(np.atleast_1d(axes))
        return fig, axes

    monkeypatch.setattr(plt, "subplots", spy_subplots)
    exports = {
        name: np.zeros((8, 8), np.uint8)
        for name in ("original_image", "preprocessed_image", "segmentation",
                     "erosion_result", "final_dilated_result")
    }
    assert test_pipeline.show_panel(exports) is True
    assert shown == [True]
    assert drawn["n_axes"] == 5


@pytest.mark.parametrize("mode", ["sequential", "parallel"])
def test_truncated_masks_counted_not_failed(cohort, tmp_path, mode):
    """VERDICT r4 item 4 at driver level: a cap-truncated mask is exported
    (the slice is NOT a failure — the pair exists) but counted and logged
    per patient in the summary, the way FAST's always-completing BFS makes
    the reference's masks trustworthy by construction."""
    import dataclasses

    capped = dataclasses.replace(CFG, grow_block_iters=1, grow_max_iters=2)
    proc = CohortProcessor(cohort, tmp_path / "t", cfg=capped, mode=mode)
    summary = proc.process_all_patients()
    d = summary.as_dict()
    # the lesion slices cap out; the blank first slices converge
    assert d["slices_truncated"] > 0
    assert d["slices_ok"] == 8  # truncation is not failure
    for pid, rec in d["per_patient"].items():
        assert rec["truncated"] <= rec["total"]
    # the flag costs nothing on the default config: nothing truncates there
    ok = CohortProcessor(cohort, tmp_path / "ok", cfg=CFG, mode=mode)
    assert ok.process_all_patients().as_dict()["slices_truncated"] == 0
    # truncated gets its own manifest status, so the warning's remedy works:
    # a --resume rerun with the cap raised recomputes exactly those slices
    # and the record comes back clean
    redo = CohortProcessor(
        cohort, tmp_path / "t", cfg=CFG, mode=mode, resume=True
    )
    d2 = redo.process_all_patients().as_dict()
    assert d2["slices_truncated"] == 0
    assert d2["slices_ok"] == 8
