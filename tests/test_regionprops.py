"""connected_components / region_properties vs the scipy.ndimage oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import ndimage

from nm03_capstone_project_tpu.ops.regionprops import (
    connected_components,
    region_properties,
)


def _random_mask(rng, h=48, w=40, p=0.35):
    return rng.random((h, w)) < p


def _canonical(labels: np.ndarray) -> np.ndarray:
    """Relabel to consecutive ints in first-occurrence order for comparison."""
    out = np.zeros_like(labels)
    nxt = 1
    seen = {}
    for v in labels.ravel():
        if v != 0 and v not in seen:
            seen[v] = nxt
            nxt += 1
    for v, k in seen.items():
        out[labels == v] = k
    return out


@pytest.mark.parametrize("connectivity", [4, 8])
def test_components_match_scipy(rng, connectivity):
    structure = (
        ndimage.generate_binary_structure(2, 1)
        if connectivity == 4
        else ndimage.generate_binary_structure(2, 2)
    )
    for seed in range(3):
        m = _random_mask(np.random.default_rng(seed))
        ours = np.asarray(connected_components(jnp.asarray(m), connectivity))
        ref, _ = ndimage.label(m, structure=structure)
        assert (ours > 0).sum() == (ref > 0).sum()
        np.testing.assert_array_equal(_canonical(ours), _canonical(ref))


def test_components_no_wraparound():
    # a component touching the left edge must not join one touching the right
    m = np.zeros((8, 8), bool)
    m[:, 0] = True
    m[:, -1] = True
    lab = np.asarray(connected_components(jnp.asarray(m)))
    assert len(np.unique(lab[lab > 0])) == 2


def test_components_empty_and_full():
    assert np.asarray(connected_components(jnp.zeros((16, 16), bool))).sum() == 0
    full = np.asarray(connected_components(jnp.ones((16, 16), bool)))
    assert len(np.unique(full)) == 1  # one component, label 1


def test_region_properties_ranked_areas(rng):
    m = np.zeros((64, 64), bool)
    m[2:6, 2:6] = True        # area 16
    m[20:30, 20:40] = True    # area 200
    m[50:53, 50:52] = True    # area 6
    props = jax.jit(lambda x: region_properties(x, max_regions=4))(jnp.asarray(m))
    area = np.asarray(props["area"])
    assert list(area) == [200, 16, 6, 0]
    # largest region centroid and bbox
    np.testing.assert_allclose(np.asarray(props["centroid"])[0], [24.5, 29.5])
    np.testing.assert_array_equal(np.asarray(props["bbox"])[0], [20, 20, 29, 39])
    # empty slot is -1-filled
    assert np.asarray(props["label"])[3] == -1
    np.testing.assert_array_equal(np.asarray(props["bbox"])[3], [-1, -1, -1, -1])


def test_region_properties_matches_scipy_on_random(rng):
    m = _random_mask(np.random.default_rng(7), p=0.3)
    props = region_properties(jnp.asarray(m), max_regions=5)
    ref, n = ndimage.label(m, structure=ndimage.generate_binary_structure(2, 1))
    sizes = np.sort(ndimage.sum_labels(np.ones_like(ref), ref, range(1, n + 1)))[::-1]
    ours = np.asarray(props["area"])
    expect = sizes[:5].astype(int)
    np.testing.assert_array_equal(ours[: len(expect)], expect)


def test_serpentine_component_converges():
    # one snake-shaped component whose propagation path is ~h*w long;
    # the default max_iters (h*w) must fully converge it to one label
    h, w = 24, 24
    m = np.zeros((h, w), bool)
    for r in range(0, h, 2):
        m[r, :] = True
        if r + 1 < h:
            m[r + 1, -1 if (r // 2) % 2 == 0 else 0] = True
    lab = np.asarray(connected_components(jnp.asarray(m)))
    assert len(np.unique(lab[lab > 0])) == 1


def test_region_properties_rejects_batched_mask():
    with pytest.raises(ValueError, match="vmap"):
        region_properties(jnp.zeros((2, 8, 8), bool))


def test_region_properties_vmaps():
    m = np.zeros((2, 16, 16), bool)
    m[0, 2:6, 2:6] = True
    m[1, 1:3, 1:9] = True
    props = jax.vmap(lambda x: region_properties(x, max_regions=2))(jnp.asarray(m))
    np.testing.assert_array_equal(np.asarray(props["area"]), [[16, 0], [16, 0]])


class TestBoundingBox:
    def test_matches_scipy_objects(self, rng):
        from nm03_capstone_project_tpu.ops.regionprops import bounding_box

        m = _random_mask(rng)
        box = np.asarray(bounding_box(jnp.asarray(m)))
        (sl_y, sl_x), = ndimage.find_objects(m.astype(np.int32))
        assert tuple(box) == (
            sl_y.start, sl_x.start, sl_y.stop - 1, sl_x.stop - 1
        )

    def test_empty_mask_is_sentinel(self):
        from nm03_capstone_project_tpu.ops.regionprops import bounding_box

        box = np.asarray(bounding_box(jnp.zeros((8, 8), bool)))
        np.testing.assert_array_equal(box, [-1, -1, -1, -1])

    def test_vmaps_over_batch(self, rng):
        from nm03_capstone_project_tpu.ops.regionprops import bounding_box

        batch = np.stack([_random_mask(rng) for _ in range(3)])
        boxes = np.asarray(jax.vmap(bounding_box)(jnp.asarray(batch)))
        assert boxes.shape == (3, 4)
        for m, b in zip(batch, boxes):
            single = np.asarray(bounding_box(jnp.asarray(m)))
            np.testing.assert_array_equal(b, single)

    def test_tiny_regionprops_mask_smaller_than_max_regions(self):
        # regression: top_k used to require max_regions <= h*w+1
        r = region_properties(jnp.ones((2, 3), bool), max_regions=8)
        assert int(r["area"][0]) == 6
        assert int((r["area"] > 0).sum()) == 1
