"""Test-only ctypes binding to the system CharLS library (libcharls.so.2).

CharLS is an INDEPENDENT, widely-deployed JPEG-LS (ITU-T T.87) codec; the
suite uses it as the conformance oracle for this repo's from-scratch JPEG-LS
decoders (Python data/codecs.py + native csrc) — closing the VERDICT r3
"codec tests are self-referential" gap with externally-produced streams.

Only tests import this module. The framework's own decoders never link or
dlopen CharLS; a machine without libcharls still runs the suite against the
pre-generated vectors vendored in tests/golden/jpegls/.
"""

from __future__ import annotations

import ctypes
import ctypes.util

import numpy as np


class _FrameInfo(ctypes.Structure):
    # charls/public_types.h: charls_frame_info
    _fields_ = [
        ("width", ctypes.c_uint32),
        ("height", ctypes.c_uint32),
        ("bits_per_sample", ctypes.c_int32),
        ("component_count", ctypes.c_int32),
    ]


_lib = None


def load():
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("charls") or "libcharls.so.2"
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        return None
    lib.charls_jpegls_encoder_create.restype = ctypes.c_void_p
    lib.charls_jpegls_decoder_create.restype = ctypes.c_void_p
    for fn, argtypes in {
        "charls_jpegls_encoder_destroy": [ctypes.c_void_p],
        "charls_jpegls_encoder_set_frame_info": [
            ctypes.c_void_p, ctypes.POINTER(_FrameInfo)],
        "charls_jpegls_encoder_set_near_lossless": [
            ctypes.c_void_p, ctypes.c_int32],
        "charls_jpegls_encoder_set_destination_buffer": [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t],
        "charls_jpegls_encoder_get_estimated_destination_size": [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t)],
        "charls_jpegls_encoder_encode_from_buffer": [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32],
        "charls_jpegls_encoder_get_bytes_written": [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t)],
        "charls_jpegls_decoder_destroy": [ctypes.c_void_p],
        "charls_jpegls_decoder_set_source_buffer": [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t],
        "charls_jpegls_decoder_read_header": [ctypes.c_void_p],
        "charls_jpegls_decoder_get_frame_info": [
            ctypes.c_void_p, ctypes.POINTER(_FrameInfo)],
        "charls_jpegls_decoder_get_destination_size": [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_size_t)],
        "charls_jpegls_decoder_decode_to_buffer": [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32],
    }.items():
        getattr(lib, fn).argtypes = argtypes
        if fn.endswith(("destroy",)):
            getattr(lib, fn).restype = None
        elif not fn.endswith("create"):
            getattr(lib, fn).restype = ctypes.c_int32
    _lib = lib
    return lib


def available() -> bool:
    return load() is not None


def _check(rc: int, what: str) -> None:
    if rc != 0:
        raise RuntimeError(f"charls {what} failed: errc={rc}")


def encode(image: np.ndarray, near: int = 0) -> bytes:
    """Encode a 2D uint8/uint16 array as a JPEG-LS stream via CharLS."""
    lib = load()
    if lib is None:
        raise RuntimeError("libcharls unavailable")
    arr = np.ascontiguousarray(image)
    assert arr.ndim == 2 and arr.dtype in (np.uint8, np.uint16)
    bits = 8 if arr.dtype == np.uint8 else int(arr.max()).bit_length()
    bits = max(bits, 2) if arr.dtype == np.uint16 else 8
    if arr.dtype == np.uint16 and bits <= 8:
        # CharLS reads ONE byte per sample when bits_per_sample <= 8: a
        # uint16 buffer would be encoded as its raw byte stream (low/high
        # interleave), silently corrupting the oracle
        arr = np.ascontiguousarray(arr.astype(np.uint8))
    enc = lib.charls_jpegls_encoder_create()
    try:
        info = _FrameInfo(arr.shape[1], arr.shape[0], bits, 1)
        _check(lib.charls_jpegls_encoder_set_frame_info(enc, ctypes.byref(info)),
               "set_frame_info")
        _check(lib.charls_jpegls_encoder_set_near_lossless(enc, near),
               "set_near_lossless")
        size = ctypes.c_size_t()
        _check(lib.charls_jpegls_encoder_get_estimated_destination_size(
            enc, ctypes.byref(size)), "estimated_size")
        out = (ctypes.c_ubyte * size.value)()
        _check(lib.charls_jpegls_encoder_set_destination_buffer(
            enc, out, size.value), "set_destination")
        _check(lib.charls_jpegls_encoder_encode_from_buffer(
            enc, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, 0), "encode")
        written = ctypes.c_size_t()
        _check(lib.charls_jpegls_encoder_get_bytes_written(
            enc, ctypes.byref(written)), "bytes_written")
        return bytes(bytearray(out[: written.value]))
    finally:
        lib.charls_jpegls_encoder_destroy(enc)


def decode(data: bytes):
    """Decode a JPEG-LS stream via CharLS -> (array, near)."""
    lib = load()
    if lib is None:
        raise RuntimeError("libcharls unavailable")
    dec = lib.charls_jpegls_decoder_create()
    try:
        buf = (ctypes.c_ubyte * len(data)).from_buffer_copy(data)
        _check(lib.charls_jpegls_decoder_set_source_buffer(
            dec, buf, len(data)), "set_source")
        _check(lib.charls_jpegls_decoder_read_header(dec), "read_header")
        info = _FrameInfo()
        _check(lib.charls_jpegls_decoder_get_frame_info(
            dec, ctypes.byref(info)), "get_frame_info")
        size = ctypes.c_size_t()
        _check(lib.charls_jpegls_decoder_get_destination_size(
            dec, 0, ctypes.byref(size)), "destination_size")
        out = (ctypes.c_ubyte * size.value)()
        _check(lib.charls_jpegls_decoder_decode_to_buffer(
            dec, out, size.value, 0), "decode")
        dtype = np.uint8 if info.bits_per_sample <= 8 else np.uint16
        arr = np.frombuffer(bytearray(out), dtype=dtype).reshape(
            info.height, info.width
        )
        return arr.copy()
    finally:
        lib.charls_jpegls_decoder_destroy(dec)
