"""Externally-produced DICOM conformance vectors (VERDICT r3 item 6).

The files in tests/golden/dicom/ were written by GDCM — an independent,
widely-deployed DICOM implementation — via tests/golden/dicom/
make_vectors.cpp, NOT by this repo's writer. Both readers (Python
data/dicomlite.py and the native C++ parser) must decode every transfer
syntax bit-exactly against the deterministic pattern the generator embeds,
which this module recomputes independently in numpy.

Syntaxes covered: Explicit VR LE, Implicit VR LE, RLE Lossless, and
JPEG Lossless SV1 (1.2.840.10008.1.2.4.70), in 16-bit and 8-bit.
(JPEG-LS vectors come from CharLS in tests/test_jpegls.py.)
"""

import pathlib

import numpy as np
import pytest

GOLDEN = pathlib.Path(__file__).parent / "golden" / "dicom"
ROWS, COLS = 60, 48


def pattern16() -> np.ndarray:
    y, x = np.indices((ROWS, COLS))
    return (((y // 4) * 251 + (x // 4) * 97 + y * x) % 4096).astype(np.uint16)


def pattern8() -> np.ndarray:
    y, x = np.indices((ROWS, COLS))
    return ((y * 7 + (x // 8) * 31) % 256).astype(np.uint8)


CASES = [
    ("gdcm16_explicit.dcm", pattern16),
    ("gdcm16_implicit.dcm", pattern16),
    ("gdcm16_bigendian.dcm", pattern16),
    ("gdcm16_deflated.dcm", pattern16),
    ("gdcm16_rle.dcm", pattern16),
    ("gdcm16_jpegll.dcm", pattern16),
    ("gdcm8_explicit.dcm", pattern8),
    ("gdcm8_rle.dcm", pattern8),
    ("gdcm8_jpegll.dcm", pattern8),
]


class TestPythonReader:
    @pytest.mark.parametrize("name,make", CASES)
    def test_decodes_gdcm_file_bit_exact(self, name, make):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        s = read_dicom(GOLDEN / name)
        assert s.pixels.shape == (ROWS, COLS)
        np.testing.assert_array_equal(
            s.pixels.astype(np.int64), make().astype(np.int64)
        )


class TestNativeReader:
    @pytest.fixture(scope="class")
    def native(self):
        from nm03_capstone_project_tpu import native

        if not native.available():
            pytest.skip("native layer unavailable")
        return native

    # deflated is Python-reader-only (the runner's per-slice retry covers
    # it on the native path, like baseline JPEG)
    @pytest.mark.parametrize(
        "name,make", [c for c in CASES if "deflated" not in c[0]]
    )
    def test_decodes_gdcm_file_bit_exact(self, native, name, make):
        px = native.read_dicom_native(GOLDEN / name)
        assert px.shape == (ROWS, COLS)
        np.testing.assert_array_equal(
            px.astype(np.int64), make().astype(np.int64)
        )


class TestJ2KFallback:
    """JPEG 2000 routes through the optional GDCM shim when present; the
    transcode-remedy rejection is preserved when it is disabled/absent."""

    @pytest.fixture(scope="class")
    def fallback(self):
        from nm03_capstone_project_tpu.data import gdcm_fallback

        if not gdcm_fallback.available():
            pytest.skip("gdcm fallback unavailable on this host")
        return gdcm_fallback

    @pytest.mark.parametrize(
        "name,make", [("gdcm16_j2k.dcm", pattern16), ("gdcm8_j2k.dcm", pattern8)]
    )
    def test_j2k_lossless_decodes_bit_exact(self, fallback, name, make):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        s = read_dicom(GOLDEN / name)
        np.testing.assert_array_equal(
            s.pixels.astype(np.int64), make().astype(np.int64)
        )

    def test_disabled_fallback_rejects_with_remedy(self, monkeypatch):
        # NM03_NO_GDCM pins the no-GDCM behavior even on hosts that have it
        import nm03_capstone_project_tpu.data.gdcm_fallback as gf
        from nm03_capstone_project_tpu.data.dicomlite import (
            DicomParseError,
            read_dicom,
        )

        monkeypatch.setattr(gf, "available", lambda: False)
        with pytest.raises(DicomParseError, match="transcode"):
            read_dicom(GOLDEN / "gdcm16_j2k.dcm")


class TestPhotometricInterpretation:
    """MONOCHROME1 (inverted grayscale, PS3.3 C.7.6.3.1.2) normalizes to
    MONOCHROME2 semantics in BOTH readers; PALETTE COLOR rejects loudly
    (its stored values are LUT indexes, not intensities)."""

    def test_monochrome1_inverts_in_python_reader(self):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        want = 65535 - pattern16().astype(np.int64)
        s = read_dicom(GOLDEN / "gdcm16_mono1.dcm")
        np.testing.assert_array_equal(s.pixels.astype(np.int64), want)

    def test_monochrome1_inverts_in_native_reader(self):
        from nm03_capstone_project_tpu import native

        if not native.available():
            pytest.skip("native layer unavailable")
        want = 65535 - pattern16().astype(np.int64)
        px = native.read_dicom_native(GOLDEN / "gdcm16_mono1.dcm")
        np.testing.assert_array_equal(px.astype(np.int64), want)

    def test_signed_monochrome1_inverts_about_minus_one(self, tmp_path):
        # signed stored range is [-2^(b-1), 2^(b-1)-1], so the inversion
        # base is lo+hi = -1, NOT 2^b-1 (which would shift outputs by 2^b)
        import struct

        from nm03_capstone_project_tpu.data.dicomlite import (
            _element,
            read_dicom,
        )

        raw = np.array([[-1000, -1], [0, 1000]], np.int16)
        ds = (
            _element(0x0028, 0x0004, b"CS", b"MONOCHROME1")
            + _element(0x0028, 0x0010, b"US", struct.pack("<H", 2))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", 2))
            + _element(0x0028, 0x0100, b"US", struct.pack("<H", 16))
            + _element(0x0028, 0x0101, b"US", struct.pack("<H", 16))
            + _element(0x0028, 0x0103, b"US", struct.pack("<H", 1))
            + _element(0x7FE0, 0x0010, b"OW", raw.astype("<i2").tobytes())
        )
        p = tmp_path / "sm1.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + ds)
        s = read_dicom(p)
        np.testing.assert_array_equal(
            s.pixels.astype(np.int64), -1 - raw.astype(np.int64)
        )
        from nm03_capstone_project_tpu import native

        if native.available():
            px = native.read_dicom_native(p)
            np.testing.assert_array_equal(
                px.astype(np.int64), -1 - raw.astype(np.int64)
            )

    def test_palette_color_rejected(self, tmp_path):
        import struct

        from nm03_capstone_project_tpu.data.dicomlite import (
            DicomParseError,
            _element,
            read_dicom,
        )

        ds = (
            _element(0x0028, 0x0004, b"CS", b"PALETTE COLOR")
            + _element(0x0028, 0x0010, b"US", struct.pack("<H", 4))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", 4))
            + _element(0x0028, 0x0100, b"US", struct.pack("<H", 8))
            + _element(0x7FE0, 0x0010, b"OW", b"\x00" * 16)
        )
        p = tmp_path / "pal.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + ds)
        with pytest.raises(DicomParseError, match="PALETTE COLOR"):
            read_dicom(p)


def test_deflated_bomb_contained(tmp_path):
    # a ~1 MB deflate stream inflating to 1 GiB must hit the importer's
    # size bound as a clean DicomParseError, never an OOM
    import struct
    import zlib

    from nm03_capstone_project_tpu.data.dicomlite import (
        DicomParseError,
        _element,
        read_dicom,
    )

    z = zlib.compressobj(9, zlib.DEFLATED, -15)
    payload = z.compress(b"\x00" * (1 << 30)) + z.flush()
    meta_elems = _element(0x0002, 0x0010, b"UI", b"1.2.840.10008.1.2.1.99")
    meta = (
        _element(0x0002, 0x0000, b"UL", struct.pack("<I", len(meta_elems)))
        + meta_elems
    )
    p = tmp_path / "bomb.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + meta + payload)
    with pytest.raises(DicomParseError, match="size bound"):
        read_dicom(p)


def test_all_vectors_present():
    assert {n for n, _ in CASES} <= {p.name for p in GOLDEN.glob("*.dcm")}


class TestStoredBits:
    """BitsStored < BitsAllocated: high bits are overlay/garbage and must be
    masked (unsigned) or sign-extended from the stored sign bit (signed), as
    DCMTK does; exotic HighBit packings reject with a remedy."""

    @staticmethod
    def _file(tmp_path, raw16, bits_stored, signed=False, high_bit=None):
        import struct

        from nm03_capstone_project_tpu.data.dicomlite import _element

        ds = (
            _element(0x0028, 0x0010, b"US", struct.pack("<H", raw16.shape[0]))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", raw16.shape[1]))
            + _element(0x0028, 0x0100, b"US", struct.pack("<H", 16))
            + _element(0x0028, 0x0101, b"US", struct.pack("<H", bits_stored))
            + _element(
                0x0028, 0x0102, b"US",
                struct.pack("<H", bits_stored - 1 if high_bit is None else high_bit),
            )
            + _element(0x0028, 0x0103, b"US", struct.pack("<H", 1 if signed else 0))
            + _element(0x7FE0, 0x0010, b"OW", raw16.astype("<u2").tobytes())
        )
        p = tmp_path / "bs.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + ds)
        return p

    def test_unsigned_high_bits_masked(self, tmp_path):
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        # 12-bit stored with overlay garbage in bits 12-15
        raw = np.array([[0xF123, 0x0FFF], [0x8000, 0x0001]], np.uint16)
        want = (raw & 0x0FFF).astype(np.int64)
        p = self._file(tmp_path, raw, bits_stored=12)
        np.testing.assert_array_equal(
            read_dicom(p).pixels.astype(np.int64), want
        )
        if native.available():
            np.testing.assert_array_equal(
                native.read_dicom_native(p).astype(np.int64), want
            )

    def test_signed_sign_extends_from_stored_bit(self, tmp_path):
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        # 12-bit signed: 0x0800 is -2048, garbage high bits ignored
        raw = np.array([[0xF800, 0x07FF], [0x0800, 0x0000]], np.uint16)
        want = np.array([[-2048, 2047], [-2048, 0]], np.int64)
        p = self._file(tmp_path, raw, bits_stored=12, signed=True)
        np.testing.assert_array_equal(
            read_dicom(p).pixels.astype(np.int64), want
        )
        if native.available():
            np.testing.assert_array_equal(
                native.read_dicom_native(p).astype(np.int64), want
            )

    def test_exotic_high_bit_rejected(self, tmp_path):
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import (
            DicomParseError,
            read_dicom,
        )

        raw = np.zeros((2, 2), np.uint16)
        p = self._file(tmp_path, raw, bits_stored=12, high_bit=15)
        with pytest.raises(DicomParseError, match="HighBit"):
            read_dicom(p)
        if native.available():
            with pytest.raises(ValueError, match="HighBit"):
                native.read_dicom_native(p)

    def test_zero_bits_stored_rejected_by_both_readers(self, tmp_path):
        # BitsStored=0 must reject identically in both readers — the old
        # `or bits` coalescing silently accepted it on the Python side
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import (
            DicomParseError,
            read_dicom,
        )

        raw = np.zeros((2, 2), np.uint16)
        p = self._file(tmp_path, raw, bits_stored=0, high_bit=0)
        with pytest.raises(DicomParseError, match="BitsStored"):
            read_dicom(p)
        if native.available():
            with pytest.raises(ValueError, match="BitsStored"):
                native.read_dicom_native(p)


def pattern16_odd() -> np.ndarray:
    y, x = np.indices((59, 47))
    return (((y // 4) * 251 + (x // 4) * 97 + y * x) % 4096).astype(np.uint16)


def multiframe_frame(f: int) -> np.ndarray:
    """The generator's per-frame pattern: frame index XORed into each
    sample's low byte (make_vectors.cpp write_multiframe)."""
    y, x = np.indices((32, 28))
    base = (((y // 4) * 251 + (x // 4) * 97 + y * x) % 4096).astype(np.uint16)
    return (base & 0xFF00) | ((base & 0xFF) ^ (f * 31))


class TestRealArchiveShapes:
    """Round-5 conformance widening (VERDICT r4 item 7): odd dims,
    presentation tags, multi-frame — the shapes real TCIA-style archives
    carry that the synthetic cohort does not."""

    @pytest.mark.parametrize(
        "name", ["gdcm16_odd.dcm", "gdcm16_odd_jpegll.dcm"]
    )
    def test_odd_dims_bit_exact_python(self, name):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        s = read_dicom(GOLDEN / name)
        assert s.pixels.shape == (59, 47)
        np.testing.assert_array_equal(
            s.pixels.astype(np.int64), pattern16_odd().astype(np.int64)
        )

    @pytest.mark.parametrize(
        "name", ["gdcm16_odd.dcm", "gdcm16_odd_jpegll.dcm"]
    )
    def test_odd_dims_bit_exact_native(self, name):
        from nm03_capstone_project_tpu import native

        if not native.available():
            pytest.skip("native layer unavailable")
        got = native.read_dicom_native(GOLDEN / name)
        np.testing.assert_array_equal(
            got.astype(np.int64), pattern16_odd().astype(np.int64)
        )

    def test_window_and_planar_tags_do_not_disturb_pixels(self):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        s = read_dicom(GOLDEN / "gdcm16_window.dcm")
        np.testing.assert_array_equal(
            s.pixels.astype(np.int64), pattern16().astype(np.int64)
        )
        # multi-valued DS: the first (center, width) pair surfaces
        assert s.window == (1024.0, 512.0)
        # a stray PlanarConfiguration on monochrome is presentation noise
        assert s.meta.get((0x0028, 0x0006)) is not None

    @pytest.mark.parametrize(
        "name", ["gdcm16_multiframe.dcm", "gdcm16_multiframe_rle.dcm"]
    )
    def test_multiframe_every_frame_bit_exact(self, name):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        for f in range(3):
            s = read_dicom(GOLDEN / name, frame=f)
            assert s.num_frames == 3
            np.testing.assert_array_equal(
                s.pixels.astype(np.int64),
                multiframe_frame(f).astype(np.int64),
                err_msg=f"{name} frame {f}",
            )

    def test_multiframe_default_is_frame_zero(self):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        s = read_dicom(GOLDEN / "gdcm16_multiframe.dcm")
        np.testing.assert_array_equal(
            s.pixels.astype(np.int64), multiframe_frame(0).astype(np.int64)
        )

    def test_out_of_range_frame_rejected(self):
        from nm03_capstone_project_tpu.data.dicomlite import (
            DicomParseError,
            read_dicom,
        )

        with pytest.raises(DicomParseError, match="frame 3 out of range"):
            read_dicom(GOLDEN / "gdcm16_multiframe.dcm", frame=3)
        with pytest.raises(DicomParseError, match="out of range"):
            read_dicom(GOLDEN / "gdcm16_multiframe_rle.dcm", frame=7)


class TestMultiframeNative:
    def test_native_serves_frame_zero(self):
        """The native reader's contract for multi-frame files: decode frame
        0 (uncompressed: leading plane; RLE: first fragment) with the frame
        count validated against the data — identical to the Python reader's
        default, so the batch loader needs no fallback for these."""
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        if not native.available():
            pytest.skip("native layer unavailable")
        for name in ("gdcm16_multiframe.dcm", "gdcm16_multiframe_rle.dcm"):
            nat = native.read_dicom_native(GOLDEN / name)
            py = read_dicom(GOLDEN / name).pixels
            np.testing.assert_array_equal(nat, py, err_msg=name)


class TestMultiframeJpegParity:
    def test_lying_frame_count_rejected_by_both_readers(self, tmp_path):
        """A JPEG-lossless file declaring NumberOfFrames=3 over a single
        codestream must reject in BOTH readers (the codestream count is
        validated against the header) — acceptance parity, like every other
        shared-envelope shape."""
        import struct

        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data import codecs
        from nm03_capstone_project_tpu.data.dicomlite import (
            _element,
            _ITEM,
            _SEQ_DELIM,
            DicomParseError,
            JPEG_LOSSLESS,
            read_dicom,
        )

        img = np.arange(64, dtype=np.uint16).reshape(8, 8)
        frag = codecs.jpeg_lossless_encode(img)
        if len(frag) % 2:
            frag += b"\x00"
        items = struct.pack("<HHI", *_ITEM, 0)
        items += struct.pack("<HHI", *_ITEM, len(frag)) + frag
        items += struct.pack("<HHI", *_SEQ_DELIM, 0)
        meta_elems = _element(0x0002, 0x0010, b"UI", JPEG_LOSSLESS.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", struct.pack("<I", len(meta_elems)))
            + meta_elems
        )
        ds = (
            _element(0x0028, 0x0002, b"US", struct.pack("<H", 1))
            + _element(0x0028, 0x0008, b"IS", b"3 ")
            + _element(0x0028, 0x0010, b"US", struct.pack("<H", 8))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", 8))
            + _element(0x0028, 0x0100, b"US", struct.pack("<H", 16))
            + _element(0x0028, 0x0103, b"US", struct.pack("<H", 0))
            + struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
            + items
        )
        p = tmp_path / "lying.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
        with pytest.raises(DicomParseError, match="codestream"):
            read_dicom(p)
        if native.available():
            with pytest.raises(ValueError):
                native.read_dicom_native(p)


class TestReadDicomFrames:
    def test_parse_once_matches_per_frame_reads(self):
        from nm03_capstone_project_tpu.data.dicomlite import (
            read_dicom,
            read_dicom_frames,
        )

        frames = read_dicom_frames(GOLDEN / "gdcm16_multiframe.dcm")
        assert len(frames) == 3
        for k, s in enumerate(frames):
            want = read_dicom(GOLDEN / "gdcm16_multiframe.dcm", frame=k)
            np.testing.assert_array_equal(s.pixels, want.pixels)

    def test_single_frame_file_yields_one(self):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom_frames

        frames = read_dicom_frames(GOLDEN / "gdcm16_explicit.dcm")
        assert len(frames) == 1 and frames[0].pixels.shape == (ROWS, COLS)
