"""Saturation & goodput telemetry tests (ISSUE 10).

Layers, mirroring the serving test files' structure:

* jax-free units: the sliding-window ring (eviction, bounds, injected
  monotonic clock), idle gaps, MFU from a pinned fake cost table, the
  peak-flops table, and the PhaseAccountant's interval algebra;
* padding/occupancy math against a lane-aware fake batcher (no jax);
* the ``--expect-gauge-range`` red/green battery (subprocess, like the
  other check_telemetry hooks);
* the jax-compilation-cache sidecar wiring;
* driver feed_stall reports (both batch drivers, in-process) and bench's
  checksum-gated ``feed_stall`` record;
* ``nm03-top --once --format json`` against an in-process server;
* the acceptance subprocess drill: ``nm03-serve --lanes 4`` under a real
  ``nm03-loadgen`` run — every lane's busy fraction > 0, padding ratio
  in [0, 1), MFU > 0, gated by labeled ``--expect-gauge-range``
  expectations, with ``nm03-top --once`` rendering the same numbers from
  the live server.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from nm03_capstone_project_tpu.obs.metrics import MetricsRegistry
from nm03_capstone_project_tpu.obs.saturation import (
    CPU_PEAK_FLOPS_ESTIMATE,
    PhaseAccountant,
    SaturationMonitor,
    peak_flops_for,
)
from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue, ServeRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


class FakeClock:
    """Injected monotonic clock for deterministic window math."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# -- sliding-window units ----------------------------------------------------


class TestSaturationWindow:
    def test_busy_fraction_over_window(self):
        clk = FakeClock()
        mon = SaturationMonitor(window_s=10.0, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        t0 = clk.t
        mon.record_dispatch(0, t0, t0 + 2.0)
        clk.advance(4.0)
        snap = mon.snapshot()
        # window start clamps to the epoch: 2 busy seconds over 4 elapsed
        assert snap["lanes"][0]["busy_fraction"] == pytest.approx(0.5)
        assert snap["busy_fraction"] == pytest.approx(0.5)

    def test_overlapping_intervals_union_not_sum(self):
        clk = FakeClock()
        mon = SaturationMonitor(window_s=10.0, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        t0 = clk.t
        # two overlapping dispatches (a requeue landing on a busy lane)
        mon.record_dispatch(0, t0, t0 + 2.0)
        mon.record_dispatch(0, t0 + 1.0, t0 + 3.0)
        clk.advance(4.0)
        # union is 3 s, not 4 — a fraction > 1 would be nonsense
        assert mon.snapshot()["lanes"][0]["busy_fraction"] == pytest.approx(
            0.75
        )

    def test_eviction_slides_old_busy_out(self):
        clk = FakeClock()
        mon = SaturationMonitor(window_s=5.0, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        mon.record_dispatch(0, clk.t, clk.t + 1.0)
        clk.advance(100.0)  # far past the window
        snap = mon.snapshot()
        assert snap["lanes"][0]["busy_fraction"] == 0.0
        # the ring itself was evicted, not just clipped to zero weight
        assert len(mon._dispatches[0]) == 0

    def test_ring_is_bounded(self):
        clk = FakeClock()
        mon = SaturationMonitor(window_s=1e9, max_entries=8, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        for i in range(100):
            mon.record_dispatch(0, clk.t + i, clk.t + i + 0.5)
        assert len(mon._dispatches[0]) == 8

    def test_idle_gap_histogram(self):
        clk = FakeClock()
        reg = MetricsRegistry()
        mon = SaturationMonitor(registry=reg, window_s=60.0, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        t0 = clk.t
        mon.record_dispatch(0, t0, t0 + 1.0)
        mon.record_dispatch(0, t0 + 3.0, t0 + 4.0)  # 2 s gap
        h = reg.get("serving_lane_idle_gap_seconds", lane="0")
        assert h is not None and h.count == 1
        assert h.sum == pytest.approx(2.0)

    def test_lane_gauges_exist_at_zero_from_resolution(self):
        reg = MetricsRegistry()
        mon = SaturationMonitor(registry=reg)
        mon.set_lanes([("cpu", ""), ("cpu", "")])
        for lane in ("0", "1"):
            g = reg.get("serving_lane_busy_fraction", lane=lane)
            assert g is not None and g.value == 0.0

    def test_mfu_from_pinned_fake_cost_table(self):
        clk = FakeClock()
        mon = SaturationMonitor(window_s=10.0, clock=clk)
        # fake platform with a real peak via cpu; pin flops per dispatch
        mon.set_lanes([("cpu", "cpu"), ("cpu", "cpu")])
        mon.set_lane_bucket_flops(0, 4, 1e9)
        mon.set_lane_bucket_flops(1, 4, 1e9)
        t0 = clk.t
        # 4 dispatches on lane 0, 1 on lane 1, over 2 s of window
        for i in range(4):
            mon.record_dispatch(0, t0 + i * 0.1, t0 + i * 0.1 + 0.05, bucket=4)
        mon.record_dispatch(1, t0, t0 + 0.05, bucket=4)
        clk.advance(2.0)
        snap = mon.snapshot()
        span = 2.0
        want0 = (4e9 / span) / CPU_PEAK_FLOPS_ESTIMATE
        want1 = (1e9 / span) / CPU_PEAK_FLOPS_ESTIMATE
        assert snap["lanes"][0]["mfu"] == pytest.approx(want0, rel=1e-3)
        assert snap["lanes"][1]["mfu"] == pytest.approx(want1, rel=1e-3)
        # process-wide: total flops over total fleet peak
        want = (5e9 / span) / (2 * CPU_PEAK_FLOPS_ESTIMATE)
        assert snap["mfu"] == pytest.approx(want, rel=1e-3)

    def test_failed_dispatch_is_busy_but_earns_no_flops(self):
        clk = FakeClock()
        mon = SaturationMonitor(window_s=10.0, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        mon.set_lane_bucket_flops(0, 2, 1e9)
        mon.record_dispatch(0, clk.t, clk.t + 1.0, bucket=2, counted=False)
        clk.advance(2.0)
        snap = mon.snapshot()
        assert snap["lanes"][0]["busy_fraction"] == pytest.approx(0.5)
        assert snap["lanes"][0]["mfu"] == 0.0

    def test_unknown_platform_has_no_mfu(self):
        clk = FakeClock()
        mon = SaturationMonitor(clock=clk)
        mon.set_lanes([("gpu", "NVIDIA H100")])
        mon.record_dispatch(0, clk.t, clk.t + 1.0, bucket=2)
        clk.advance(2.0)
        snap = mon.snapshot()
        assert snap["lanes"][0]["mfu"] is None
        assert snap["mfu"] is None

    def test_peak_table(self):
        assert peak_flops_for("cpu") == CPU_PEAK_FLOPS_ESTIMATE
        assert peak_flops_for("tpu", "TPU v4") == 275e12
        assert peak_flops_for("tpu", "TPU v5 lite") == 197e12
        # unknown TPU kind falls back conservatively, never None
        assert peak_flops_for("tpu", "TPU v99") == 45e12
        assert peak_flops_for("gpu", "H100") is None

    def test_publish_sets_gauges(self):
        clk = FakeClock()
        reg = MetricsRegistry()
        mon = SaturationMonitor(registry=reg, window_s=10.0, clock=clk)
        mon.set_lanes([("cpu", "cpu")])
        mon.set_lane_bucket_flops(0, 1, 1e9)
        mon.record_dispatch(0, clk.t, clk.t + 1.0, bucket=1)
        mon.record_chunk(3, 4)
        mon.record_window(3, 8)
        clk.advance(2.0)
        mon.publish()
        assert reg.get(
            "serving_lane_busy_fraction", lane="0"
        ).value == pytest.approx(0.5)
        assert reg.get("serving_padding_waste_ratio").value == pytest.approx(
            0.25
        )
        assert reg.get(
            "serving_window_occupancy_ratio"
        ).value == pytest.approx(3 / 8)
        assert reg.get("serving_mfu").value > 0
        assert reg.get("serving_batch_rows_total", kind="real").value == 3
        assert reg.get("serving_batch_rows_total", kind="padded").value == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            SaturationMonitor(window_s=0)
        with pytest.raises(ValueError):
            SaturationMonitor(max_entries=0)


# -- PhaseAccountant units ---------------------------------------------------


class TestPhaseAccountant:
    def test_disjoint_and_overlapping_merge(self):
        pa = PhaseAccountant()
        pa.record("dispatch", 10.0, 12.0)
        pa.record("dispatch", 11.0, 13.0)  # overlaps -> union 3
        pa.record("dispatch", 20.0, 21.0)
        assert pa.busy_seconds("dispatch") == pytest.approx(4.0)

    def test_out_of_order_threads(self):
        pa = PhaseAccountant()
        pa.record("decode", 20.0, 21.0)
        pa.record("decode", 10.0, 11.0)  # arrives late (another thread)
        pa.record("decode", 10.5, 20.5)  # bridges both
        assert pa.busy_seconds("decode") == pytest.approx(11.0)

    def test_stall_ratio_and_report(self):
        pa = PhaseAccountant()
        pa.record("decode", 0.0, 2.0)
        pa.record("dispatch", 2.0, 8.0)
        pa.record("export", 8.0, 10.0)
        rep = pa.report()
        assert rep["wall_s"] == pytest.approx(10.0)
        assert rep["busy_s"]["dispatch"] == pytest.approx(6.0)
        assert rep["feed_stall_ratio"] == pytest.approx(0.4)
        assert rep["stall_s"] == pytest.approx(4.0)
        assert rep["busy_fraction"]["decode"] == pytest.approx(0.2)

    def test_no_dispatch_means_null_stall(self):
        pa = PhaseAccountant()
        pa.record("decode", 0.0, 1.0)
        rep = pa.report()
        assert rep["feed_stall_ratio"] is None
        assert rep["stall_s"] is None

    def test_busy_context_uses_injected_clock(self):
        clk = FakeClock()
        pa = PhaseAccountant(clock=clk)
        with pa.busy("fetch"):
            clk.advance(1.5)
        assert pa.busy_seconds("fetch") == pytest.approx(1.5)

    def test_bounded_collapse_keeps_exact_totals(self):
        pa = PhaseAccountant(max_intervals=8)
        # 100 disjoint 0.5 s intervals: far past the cap
        for i in range(100):
            pa.record("dispatch", float(i), i + 0.5)
        assert len(pa._runs["dispatch"]) <= 8
        assert pa.busy_seconds("dispatch") == pytest.approx(50.0)
        rep = pa.report()
        assert rep["wall_s"] == pytest.approx(99.5)
        assert rep["feed_stall_ratio"] == pytest.approx(
            1 - 50.0 / 99.5, abs=1e-3
        )

    def test_late_interval_never_double_counts_collapsed_time(self):
        # a slow worker's interval arriving AFTER its time range was
        # collapsed into the closed sum must not count that range twice
        pa = PhaseAccountant(max_intervals=8)
        for i in range(20):  # trips the collapse; [0, 9.5) mostly closed
            pa.record("export", float(i), i + 0.5)
        before = pa.busy_seconds("export")
        pa.record("export", 0.0, 2.0)  # overlaps the collapsed prefix
        # the clamp forfeits the pre-horizon part; busy may only grow by
        # genuinely-new post-horizon time, never by re-counting [0, 2)
        assert pa.busy_seconds("export") <= before + 2.0 - 1.0
        assert pa.busy_seconds("export") <= 20 * 0.5 + 1.5

    def test_busy_records_on_raise(self):
        clk = FakeClock()
        pa = PhaseAccountant(clock=clk)
        with pytest.raises(RuntimeError):
            with pa.busy("decode"):
                clk.advance(1.0)
                raise RuntimeError("decoder died")
        assert pa.busy_seconds("decode") == pytest.approx(1.0)


# -- batcher goodput math against a lane-aware fake --------------------------


class FakeSaturatedExecutor:
    """Lane-aware executor stand-in carrying a real SaturationMonitor."""

    supports_trace = False

    def __init__(self, buckets=(1, 2, 4), lanes=4, canvas=16, min_dim=4,
                 clock=None):
        self.cfg = SimpleNamespace(canvas=canvas, min_dim=min_dim)
        self.buckets = tuple(buckets)
        self.lane_count = lanes
        self.registry = MetricsRegistry()
        self.saturation = SaturationMonitor(
            registry=self.registry, clock=clock or time.monotonic
        )
        self.saturation.set_lanes([("cpu", "cpu")] * lanes)
        self.calls = []
        self._lock = threading.Lock()

    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run_batch(self, pixels, dims, lane=0):
        with self._lock:
            self.calls.append((pixels.shape[0], lane))
        mask = (pixels > 0).astype(np.uint8)
        return mask, np.ones(pixels.shape[0], bool)


def _reqs(n, hw=16):
    return [
        ServeRequest(
            request_id=f"r{i}",
            pixels=np.ones((hw, hw), np.float32),
            dims=(hw, hw),
        )
        for i in range(n)
    ]


class TestBatcherGoodput:
    def test_padding_and_occupancy_accounting(self):
        # no bucket-1: the 1-rider tail chunk MUST pad into bucket 2
        ex = FakeSaturatedExecutor(buckets=(2, 4), lanes=4)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        # 7 riders over 4 lanes: per = ceil(7/4)=2 -> chunks 2,2,2,1 — the
        # last chunk pads 1 dead row into bucket 2
        b.execute(_reqs(7))
        snap = ex.saturation.snapshot()
        assert snap["rows"] == {"real": 7, "padded": 1}
        assert snap["padding_waste_ratio"] == pytest.approx(1 / 8)
        # one window of 7 riders against 4 lanes x bucket 4 = 16 capacity
        assert snap["window_occupancy_ratio"] == pytest.approx(7 / 16)
        # counters + fill histogram landed in the registry
        assert ex.registry.get(
            "serving_batch_rows_total", kind="real"
        ).value == 7
        fill = ex.registry.get("serving_bucket_fill_ratio", bucket="2")
        assert fill is not None and fill.count == 4
        # three full buckets (1.0) + one half-full (0.5)
        assert fill.sum == pytest.approx(3.5)

    def test_full_windows_have_zero_waste(self):
        ex = FakeSaturatedExecutor(buckets=(1, 2, 4), lanes=2)
        b = DynamicBatcher(AdmissionQueue(32), ex, max_wait_s=0.0)
        b.execute(_reqs(8))  # 2 lanes x bucket 4, exactly
        snap = ex.saturation.snapshot()
        assert snap["rows"] == {"real": 8, "padded": 0}
        assert snap["padding_waste_ratio"] == 0.0
        assert snap["window_occupancy_ratio"] == pytest.approx(1.0)

    def test_lane_unaware_fake_records_nothing(self):
        # executors without a .saturation attr (the historical fakes) keep
        # working: the batcher's accounting is strictly opt-in
        class Bare:
            def __init__(self):
                self.cfg = SimpleNamespace(canvas=16, min_dim=4)
                self.buckets = (4,)
                self.max_batch = 4

            def bucket_for(self, n):
                return 4

            def run_batch(self, pixels, dims):
                return (pixels > 0).astype(np.uint8), np.ones(
                    pixels.shape[0], bool
                )

        b = DynamicBatcher(AdmissionQueue(8), Bare(), max_wait_s=0.0)
        b.execute(_reqs(3))  # must simply not raise


# -- the jax-compilation-cache sidecar ---------------------------------------


class TestJaxCacheSidecar:
    def test_attach_wires_jax_cache_and_stats(self, tmp_path, monkeypatch):
        import jax

        from nm03_capstone_project_tpu.compilehub import (
            ExecutableCache,
            get_hub,
            hub_jit,
        )
        from nm03_capstone_project_tpu.compilehub import persist

        monkeypatch.delenv(persist.ENV_JAX_CACHE_OPT_OUT, raising=False)
        prev_dir = jax.config.jax_compilation_cache_dir
        hub = get_hub()
        prev_cache = hub.persistent_cache()
        try:
            hub.attach_cache(ExecutableCache(str(tmp_path)))
            want = str(tmp_path / persist.JAX_CACHE_SUBDIR)
            assert jax.config.jax_compilation_cache_dir == want
            # a deferred-trace compile now writes jax cache entries
            import jax.numpy as jnp

            f = hub_jit(lambda x: (x * 3).sum())
            float(f(jnp.ones((32, 32))))
            st = hub.stats()
            assert st["jax_cache_dir"] == want
            assert st["jax_cache_entries"] >= 1
            assert st["jax_cache_bytes"] > 0
            # the honesty split survives: no executable-cache hits were
            # invented by the sidecar
            assert st["cache_hits"] == 0
        finally:
            hub.attach_cache(prev_cache)
            with contextlib.suppress(Exception):
                jax.config.update("jax_compilation_cache_dir", prev_dir)

    def test_opt_out_env(self, tmp_path, monkeypatch):
        from nm03_capstone_project_tpu.compilehub import persist

        monkeypatch.setenv(persist.ENV_JAX_CACHE_OPT_OUT, "0")
        assert persist.attach_jax_compilation_cache(tmp_path) is None

    def test_private_hub_never_repoints_process_config(self, tmp_path):
        import jax

        from nm03_capstone_project_tpu.compilehub import ExecutableCache
        from nm03_capstone_project_tpu.compilehub.hub import CompileHub

        prev = jax.config.jax_compilation_cache_dir
        hub = CompileHub()  # NOT the process hub
        hub.attach_cache(ExecutableCache(str(tmp_path)))
        assert jax.config.jax_compilation_cache_dir == prev
        assert "jax_cache_dir" not in hub.stats()


# -- driver feed_stall reports -----------------------------------------------


class TestDriverFeedStall:
    @pytest.mark.parametrize("mode", ["sequential", "parallel"])
    def test_both_drivers_emit_feed_stall(self, tmp_path, mode):
        from nm03_capstone_project_tpu.cli import parallel, sequential

        mod = sequential if mode == "sequential" else parallel
        rj = tmp_path / "r.json"
        ej = tmp_path / "e.jsonl"
        rc = mod.main(
            [
                "--synthetic", "1", "--synthetic-slices", "3",
                "--device", "cpu", "--canvas", str(CANVAS),
                "--output", str(tmp_path / "out"),
                "--results-json", str(rj), "--log-json", str(ej),
            ]
        )
        assert rc == 0
        rec = json.loads(rj.read_text())
        fs = rec["feed_stall"]
        assert fs["wall_s"] > 0
        assert 0.0 <= fs["feed_stall_ratio"] < 1.0
        assert set(fs["busy_s"]) >= {"decode", "dispatch"}
        # the gauge twin landed in the embedded snapshot
        names = {m["name"]: m for m in rec["metrics"]["metrics"]}
        assert names["pipeline_feed_stall_ratio"]["value"] == pytest.approx(
            fs["feed_stall_ratio"]
        )
        # and the event rode the stream
        events = [
            json.loads(line) for line in ej.read_text().splitlines() if line
        ]
        feed_events = [e for e in events if e["event"] == "feed_stall"]
        assert len(feed_events) == 1
        assert feed_events[0]["mode"] == mode


class TestBenchFeedStall:
    def test_record_is_checksum_gated_and_carried(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "CANVAS", 96)
        rec = bench._feed_stall_record(batch=2, reps=3)
        assert rec["checksum_ok"] is True
        assert 0.0 <= rec["feed_stall_ratio"] <= 1.0
        assert rec["busy_s"]["dispatch"] > 0
        # rides _compose via _copy_optional -> the slim line
        out = {}
        bench._copy_optional(out, {"feed_stall": rec})
        assert out["feed_stall"] is rec

    def test_mismatched_checksum_nulls_the_headline(self, monkeypatch):
        # force the fed batches to differ from the reference batch: the
        # gate must null the ratio rather than report a number measured
        # on wrong masks (same contract as the Pallas/cold-start legs)
        import bench

        monkeypatch.setattr(bench, "CANVAS", 96)
        real_make = bench._make_batch
        calls = {"n": 0}

        def skewed(batch=None):
            pixels, dims = real_make(batch)
            calls["n"] += 1
            if calls["n"] > 1:  # the ref batch is the first call
                pixels = np.zeros_like(pixels)
            return pixels, dims

        monkeypatch.setattr(bench, "_make_batch", skewed)
        rec = bench._feed_stall_record(batch=2, reps=2)
        assert rec["checksum_ok"] is False
        assert rec["feed_stall_ratio"] is None
        assert rec["stall_s"] is None
        # the evidence fields stay: an operator can still see the phases
        assert rec["busy_s"]["dispatch"] > 0


# -- nm03-top ----------------------------------------------------------------


class TestTopCli:
    def test_once_json_against_inprocess_server(self):
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice
        from nm03_capstone_project_tpu.serving import top
        from nm03_capstone_project_tpu.serving.server import (
            ServingApp,
            serve_in_thread,
        )

        app = ServingApp(
            queue_capacity=16, buckets=(1, 2), max_wait_s=0.005, lanes=1
        )
        httpd = None
        try:
            httpd, _t, port = serve_in_thread(app)
            url = f"http://127.0.0.1:{port}"
            img = phantom_slice(CANVAS, CANVAS, seed=1).astype(np.float32)
            for _ in range(3):
                app.segment(img, render=False)
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = top.main(["--url", url, "--once", "--format", "json"])
            assert rc == 0
            view = json.loads(buf.getvalue())
            assert view["schema"] == "nm03.top.v1"
            assert view["ready"] is True
            assert len(view["lanes"]) == 1
            lane = view["lanes"][0]
            assert lane["state"] == "healthy"
            assert lane["busy_fraction"] > 0
            assert lane["batches"] >= 1
            assert view["mfu"] is not None and view["mfu"] > 0
            assert 0.0 <= view["padding_waste_ratio"] < 1.0
            # one sample has no delta: rates are honest nulls
            assert view["rates_per_s"]["requests"] is None
            # the text renderer draws the same view without raising
            text = top.render_text(view, url)
            assert "lane" in text and "busy" in text
        finally:
            if httpd is not None:
                httpd.shutdown()
                httpd.server_close()
            app.begin_drain(reason="test")
            app.close()

    def test_unreachable_server_exits_2(self):
        from nm03_capstone_project_tpu.serving import top

        with contextlib.redirect_stderr(io.StringIO()):
            rc = top.main(
                ["--url", "http://127.0.0.1:9", "--once", "--timeout-s", "1"]
            )
        assert rc == 2


# -- --expect-gauge-range battery --------------------------------------------


class TestExpectGaugeRange:
    def _snap(self, tmp_path, metrics):
        path = tmp_path / "m.json"
        path.write_text(
            json.dumps(
                {
                    "schema": "nm03.metrics.v1",
                    "run_id": "r",
                    "git_sha": "g",
                    "created_unix": 1.0,
                    "metrics": metrics,
                }
            )
        )
        return str(path)

    def _run(self, path, *flags):
        return subprocess.run(
            [sys.executable, CHECKER, "--metrics", path, *flags],
            capture_output=True, text=True, timeout=60,
        )

    def test_green_open_and_closed_bounds(self, tmp_path):
        path = self._snap(
            tmp_path,
            [
                {"name": "serving_lane_busy_fraction", "type": "gauge",
                 "labels": {"lane": "0"}, "value": 0.3},
                {"name": "serving_lane_busy_fraction", "type": "gauge",
                 "labels": {"lane": "1"}, "value": 1.0},
                {"name": "serving_padding_waste_ratio", "type": "gauge",
                 "labels": {}, "value": 0.0},
            ],
        )
        res = self._run(
            path,
            "--expect-gauge-range", "serving_lane_busy_fraction=(0..1]",
            "--expect-gauge-range", "serving_padding_waste_ratio=[0..1)",
        )
        assert res.returncode == 0, res.stderr

    def test_every_series_checked_individually(self, tmp_path):
        # one idle lane fails the every-lane form — values are NOT summed
        path = self._snap(
            tmp_path,
            [
                {"name": "serving_lane_busy_fraction", "type": "gauge",
                 "labels": {"lane": "0"}, "value": 0.9},
                {"name": "serving_lane_busy_fraction", "type": "gauge",
                 "labels": {"lane": "1"}, "value": 0.0},
            ],
        )
        res = self._run(
            path, "--expect-gauge-range", "serving_lane_busy_fraction=(0..1]"
        )
        assert res.returncode == 1
        assert "lane" in res.stderr and "(0..1]" in res.stderr

    def test_open_bound_excludes_endpoint(self, tmp_path):
        path = self._snap(
            tmp_path,
            [{"name": "serving_padding_waste_ratio", "type": "gauge",
              "labels": {}, "value": 1.0}],
        )
        res = self._run(
            path, "--expect-gauge-range", "serving_padding_waste_ratio=[0..1)"
        )
        assert res.returncode == 1

    def test_labeled_selector_composes(self, tmp_path):
        path = self._snap(
            tmp_path,
            [
                {"name": "serving_lane_busy_fraction", "type": "gauge",
                 "labels": {"lane": "0"}, "value": 0.0},
                {"name": "serving_lane_busy_fraction", "type": "gauge",
                 "labels": {"lane": "2"}, "value": 0.5},
            ],
        )
        res = self._run(
            path,
            "--expect-gauge-range",
            "serving_lane_busy_fraction{lane=2}=(0..1]",
        )
        assert res.returncode == 0, res.stderr

    def test_absent_and_unmatched_are_drift(self, tmp_path):
        path = self._snap(
            tmp_path,
            [{"name": "serving_mfu", "type": "gauge", "labels": {},
              "value": 0.1}],
        )
        assert self._run(
            path, "--expect-gauge-range", "serving_nope=[0..1]"
        ).returncode == 1
        assert self._run(
            path, "--expect-gauge-range", "serving_mfu{lane=3}=[0..1]"
        ).returncode == 1

    def test_wrong_kind_is_drift(self, tmp_path):
        path = self._snap(
            tmp_path,
            [{"name": "serving_shed_total", "type": "counter", "labels": {},
              "value": 3}],
        )
        res = self._run(
            path, "--expect-gauge-range", "serving_shed_total=[0..10]"
        )
        assert res.returncode == 1
        assert "not a gauge" in res.stderr

    def test_malformed_range_is_usage_error(self, tmp_path):
        path = self._snap(tmp_path, [])
        res = self._run(path, "--expect-gauge-range", "serving_mfu=low..high")
        assert res.returncode == 2


# -- the acceptance drill ----------------------------------------------------


class TestSaturationAcceptance:
    @pytest.mark.slow
    def test_four_lane_drill_with_loadgen_and_top(self, tmp_path):
        """The ISSUE 10 acceptance bar: ``nm03-serve --lanes 4`` under a
        32-request loadgen reports per-lane busy fractions, padding waste
        and MFU, gated by labeled ``--expect-gauge-range`` expectations
        (every lane busy > 0, padding in [0, 1), MFU > 0), with
        ``nm03-top --once`` rendering the same numbers live and
        ``nm03-loadgen`` printing the server-side efficiency columns.
        """
        port_file = tmp_path / "port"
        metrics = tmp_path / "metrics.json"
        results = tmp_path / "loadgen.json"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1,2", "--lanes", "4",
                "--max-wait-ms", "60", "--heartbeat-s", "0",
                "--metrics-out", str(metrics),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            deadline = time.monotonic() + 300
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.2)
            assert port_file.exists(), "server never became ready"
            base = f"http://127.0.0.1:{int(port_file.read_text())}"
            lg = subprocess.run(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.serving.loadgen",
                    "--url", base, "--requests", "32", "--concurrency", "16",
                    "--mode", "mask", "--height", str(CANVAS),
                    "--width", str(CANVAS), "--warmup", "4",
                    "--results-json", str(results),
                ],
                capture_output=True, text=True, timeout=300, cwd=REPO,
            )
            assert lg.returncode == 0, lg.stdout + lg.stderr
            summary = json.loads(results.read_text())
            assert summary["requests_ok"] == 32
            # the efficiency join: utilization/padding/MFU polled through
            # the run and printed next to the capacity columns
            assert summary["busy_fraction_min_observed"] is not None
            assert summary["busy_fraction_min_observed"] > 0
            assert 0.0 <= summary["padding_waste_max_observed"] < 1.0
            assert summary["mfu_max_observed"] > 0
            assert "busy_min=" in lg.stdout and "padding_max=" in lg.stdout
            # nm03-top renders the same numbers from the live server
            tp = subprocess.run(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.serving.top",
                    "--url", base, "--once", "--format", "json",
                ],
                capture_output=True, text=True, timeout=60, cwd=REPO,
            )
            assert tp.returncode == 0, tp.stdout + tp.stderr
            view = json.loads(tp.stdout)
            assert view["ready"] is True and len(view["lanes"]) == 4
            assert all(
                row["busy_fraction"] is not None and row["busy_fraction"] > 0
                for row in view["lanes"]
            ), view["lanes"]
            assert view["mfu"] > 0
            assert 0.0 <= view["padding_waste_ratio"] < 1.0
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        # the post-drain snapshot passes the labeled range gates: every
        # lane busy, padding sane, MFU real
        res = subprocess.run(
            [
                sys.executable, CHECKER,
                "--metrics", str(metrics),
                "--expect-gauge", "serving_lanes_ready=4",
                "--expect-gauge-range", "serving_lane_busy_fraction=(0..1]",
                "--expect-gauge-range", "serving_padding_waste_ratio=[0..1)",
                "--expect-gauge-range", "serving_mfu=(0..100]",
                "--expect-gauge-range", "serving_busy_fraction=(0..1]",
                "--expect-histogram", "serving_bucket_fill_ratio=4",
            ],
            capture_output=True, text=True, timeout=60,
        )
        assert res.returncode == 0, res.stderr
