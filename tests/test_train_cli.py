"""Training driver + model checkpointing end-to-end.

The CLI trains on a tiny synthetic cohort, writes an orbax checkpoint, and a
second invocation restores it for eval-only scoring — the checkpoint/resume
capability the reference lacks entirely (SURVEY.md section 5).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nm03_capstone_project_tpu.cli import train as train_cli
from nm03_capstone_project_tpu.models import init_unet, load_params, save_params

pytestmark = [pytest.mark.slow]


class TestCheckpoint:
    def test_roundtrip_params_and_meta(self, tmp_path):
        params = init_unet(jax.random.PRNGKey(0), base=8)
        save_params(tmp_path / "ck", params, meta={"base_channels": 8})
        back, meta = load_params(tmp_path / "ck")
        assert meta == {"base_channels": 8}
        for a, b in zip(
            jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_with_target_keeps_dtype(self, tmp_path):
        params = init_unet(jax.random.PRNGKey(1), base=8)
        save_params(tmp_path / "ck", params)
        target = init_unet(jax.random.PRNGKey(2), base=8)
        back, _ = load_params(tmp_path / "ck", target=target)
        assert back["head"]["w"].dtype == jnp.float32
        # restored values are the saved ones, not the target's
        assert not np.allclose(
            np.asarray(back["head"]["w"]), np.asarray(target["head"]["w"])
        )

    def test_missing_checkpoint_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_params(tmp_path / "nope")


class TestTrainCLI:
    def test_train_then_eval_only(self, tmp_path, capsys):
        out = tmp_path / "out-train"
        rc = train_cli.main(
            [
                "--synthetic", "1",
                "--synthetic-slices", "4",
                "--output", str(out),
                "--steps", "3",
                "--base-channels", "8",
                "--max-slices", "4",
                "--results-json", str(out / "train.json"),
            ]
        )
        assert rc == 0
        text = capsys.readouterr().out
        assert "checkpoint written" in text
        payload = json.loads((out / "train.json").read_text())
        assert payload["steps"] == 3 and payload["slices"] == 4
        assert np.isfinite(payload["final_loss"])

        rc = train_cli.main(
            [
                "--synthetic", "1",
                "--synthetic-slices", "4",
                "--output", str(out),
                "--restore", str(out / "checkpoint"),
                "--eval-only",
                "--base-channels", "8",
                "--max-slices", "4",
            ]
        )
        assert rc == 0
        assert "student-vs-teacher IoU" in capsys.readouterr().out

    def test_rejects_bad_canvas(self, tmp_path):
        with pytest.raises(SystemExit, match="divisible by 4"):
            train_cli.main(
                ["--synthetic", "1", "--output", str(tmp_path), "--canvas", "254"]
            )


class TestTrainCLI3D:
    """--model-3d: volumetric distillation end to end (VERDICT r1 weak #7)."""

    def test_train_3d_then_eval_only(self, tmp_path, capsys):
        out = tmp_path / "out-train3d"
        rc = train_cli.main(
            [
                "--synthetic", "1",
                "--synthetic-slices", "4",
                "--output", str(out),
                "--model-3d",
                "--volume-depth", "4",
                "--steps", "2",
                "--base-channels", "8",
                "--max-slices", "4",
                "--results-json", str(out / "train3d.json"),
            ]
        )
        assert rc == 0
        text = capsys.readouterr().out
        assert "volumetric pipeline" in text and "checkpoint written" in text
        payload = json.loads((out / "train3d.json").read_text())
        assert payload["model"] == "unet3d"
        assert payload["volumes"] == 1 and payload["steps"] == 2
        assert np.isfinite(payload["final_loss"])
        assert 0.0 <= payload["iou_vs_teacher"] <= 1.0

        rc = train_cli.main(
            [
                "--synthetic", "1",
                "--synthetic-slices", "4",
                "--output", str(out),
                "--model-3d",
                "--volume-depth", "4",
                "--restore", str(out / "checkpoint"),
                "--eval-only",
                "--max-slices", "4",
            ]
        )
        assert rc == 0
        assert "IoU over 1 volumes" in capsys.readouterr().out

    def test_dimension_checkpoint_mismatch_rejected(self, tmp_path, capsys):
        # a 2D checkpoint must not silently feed the 3D model (and vice versa)
        out = tmp_path / "out2d"
        rc = train_cli.main(
            [
                "--synthetic", "1", "--synthetic-slices", "4",
                "--output", str(out), "--steps", "1",
                "--base-channels", "8", "--max-slices", "2",
            ]
        )
        assert rc == 0
        capsys.readouterr()
        with pytest.raises(SystemExit, match="holds a 2D model"):
            train_cli.main(
                [
                    "--synthetic", "1", "--synthetic-slices", "4",
                    "--output", str(out), "--model-3d", "--volume-depth", "4",
                    "--restore", str(out / "checkpoint"), "--eval-only",
                    "--max-slices", "4",
                ]
            )

    def test_rejects_bad_volume_depth(self, tmp_path):
        with pytest.raises(SystemExit, match="volume-depth"):
            train_cli.main(
                [
                    "--synthetic", "1", "--output", str(tmp_path),
                    "--model-3d", "--volume-depth", "6",
                ]
            )
