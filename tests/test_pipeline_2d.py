import jax
import numpy as np
import pytest

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.core import pad_to_canvas
from nm03_capstone_project_tpu.data.synthetic import phantom_series, phantom_slice
from nm03_capstone_project_tpu.pipeline import (
    check_min_dims,
    process_batch,
    process_slice,
    process_slice_stages,
)

CFG = PipelineConfig(canvas=128)


@pytest.fixture(scope="module")
def small_phantom():
    return phantom_slice(128, 128, seed=3)


@pytest.mark.slow
def test_process_slice_segments_lesion(small_phantom):
    batch = pad_to_canvas([small_phantom], (128, 128))
    out = process_slice(batch.pixels[0], batch.dims[0], CFG)
    mask = np.asarray(out["mask"])
    assert mask.dtype == np.uint8
    assert set(np.unique(mask)) <= {0, 1}
    h = w = 128
    # the lesion is centered with radius 0.16*128 ~ 20px; the mask should
    # cover a blob around the center and nothing near the rim
    assert mask[h // 2, w // 2] == 1
    assert mask[: h // 8, :].sum() == 0
    area = mask.sum()
    expected_area = np.pi * (0.16 * 128) ** 2
    assert 0.5 * expected_area < area < 2.5 * expected_area
    np.testing.assert_array_equal(np.asarray(out["original"]), batch.pixels[0])


def test_stages_variant_contract(small_phantom):
    batch = pad_to_canvas([small_phantom], (128, 128))
    out = process_slice_stages(batch.pixels[0], batch.dims[0], CFG)
    assert set(out) == {
        "original_image",
        "preprocessed_image",
        "segmentation",
        "erosion_result",
        "final_dilated_result",
        "grow_converged",
    }
    seg = np.asarray(out["segmentation"])
    ero = np.asarray(out["erosion_result"])
    dil = np.asarray(out["final_dilated_result"])
    # erosion shrinks, dilation grows, both relative to the same caster output
    assert ero.sum() < seg.sum() < dil.sum()
    # erosion result is a subset of seg; seg a subset of dilation
    assert not np.any(ero & ~seg)
    assert not np.any(seg & ~dil)


@pytest.mark.slow
def test_vmapped_batch_equals_sequential():
    """Formalizes the reference's implicit parallel==sequential invariant."""
    slices = phantom_series(4, 128, 120, seed=7)
    batch = pad_to_canvas(slices, (128, 128))
    out_b = process_batch(batch.pixels, batch.dims, CFG)
    for i in range(len(slices)):
        out_s = process_slice(batch.pixels[i], batch.dims[i], CFG)
        np.testing.assert_array_equal(
            np.asarray(out_b["mask"][i]), np.asarray(out_s["mask"]), err_msg=f"slice {i}"
        )


def test_variable_dims_one_compiled_program():
    """Different true dims share one jitted program on the static canvas."""
    f = jax.jit(lambda p, d: process_slice(p, d, CFG)["mask"])
    a = phantom_slice(128, 128, seed=1)
    b = phantom_slice(110, 100, seed=1)
    batch = pad_to_canvas([a, b], (128, 128))
    m0 = np.asarray(f(batch.pixels[0], batch.dims[0]))
    m1 = np.asarray(f(batch.pixels[1], batch.dims[1]))
    assert m0[64, 64] == 1
    assert m1[55, 50] == 1
    # no segmentation in the padding of the smaller slice
    assert m1[110:, :].sum() == 0 and m1[:, 100:].sum() == 0


def test_dilation_never_spills_into_padding():
    """Regression: final dilation must be clipped to the true image extent."""
    # in-band strip connecting the central lesion to the bottom true border
    img = phantom_slice(112, 104, seed=2)
    img[56:112, 50:60] = 1600.0
    batch = pad_to_canvas([img], (128, 128))
    cfg = PipelineConfig(canvas=128)
    out = process_slice(batch.pixels[0], batch.dims[0], cfg)
    mask = np.asarray(out["mask"])
    assert mask[111, 50:60].any()  # non-vacuous: region reaches the border row
    assert mask[112:, :].sum() == 0 and mask[:, 104:].sum() == 0
    stages = process_slice_stages(batch.pixels[0], batch.dims[0], cfg)
    dil = np.asarray(stages["final_dilated_result"])
    assert dil[111, 50:60].any()
    assert dil[112:, :].sum() == 0 and dil[:, 104:].sum() == 0


def test_min_dim_guard():
    dims = np.array([[256, 256], [99, 256], [256, 12]], np.int32)
    np.testing.assert_array_equal(check_min_dims(dims), [True, False, False])


def test_golden_regression(small_phantom):
    """Pin the pipeline output so silent numeric drift fails loudly.

    If a deliberate contract change moves these numbers, update them in the
    same commit that changes the op.
    """
    batch = pad_to_canvas([small_phantom], (128, 128))
    mask = np.asarray(process_slice(batch.pixels[0], batch.dims[0], CFG)["mask"])
    area = int(mask.sum())
    ys, xs = np.nonzero(mask)
    centroid = (float(ys.mean()), float(xs.mean()))
    assert abs(centroid[0] - 63.5) < 3.0 and abs(centroid[1] - 63.5) < 3.0
    # stash the exact area in the assertion message for easy refresh
    assert 900 < area < 1800, f"golden area drifted: {area}"
