"""Host->device prefetch pipeline."""

import numpy as np

import jax

from nm03_capstone_project_tpu.data.prefetch import prefetch_to_device


class TestPrefetchToDevice:
    def test_yields_all_items_in_order(self):
        items = [{"x": np.full((4,), i, np.float32), "name": f"s{i}"} for i in range(7)]
        out = list(prefetch_to_device(iter(items), depth=2))
        assert [o["name"] for o in out] == [f"s{i}" for i in range(7)]
        for i, o in enumerate(out):
            np.testing.assert_array_equal(np.asarray(o["x"]), items[i]["x"])

    def test_arrays_land_on_device(self):
        items = [{"x": np.ones((3, 3), np.float32)}]
        (out,) = list(prefetch_to_device(iter(items), depth=2))
        assert isinstance(out["x"], jax.Array)
        assert out["x"].device == jax.devices()[0]

    def test_non_array_leaves_pass_through(self):
        items = [{"meta": "hello", "n": 3, "x": np.zeros(2)}]
        (out,) = list(prefetch_to_device(iter(items)))
        assert out["meta"] == "hello" and out["n"] == 3

    def test_empty_iterator(self):
        assert list(prefetch_to_device(iter([]))) == []

    def test_depth_one_still_works(self):
        items = [{"x": np.ones(2)} for _ in range(3)]
        assert len(list(prefetch_to_device(iter(items), depth=1))) == 3

    def test_custom_device(self):
        dev = jax.devices()[-1]
        items = [{"x": np.ones(2)}]
        (out,) = list(prefetch_to_device(iter(items), device=dev))
        assert out["x"].device == dev

    def test_none_leaves_ok(self):
        items = [{"x": None, "stems": []}, {"x": np.ones(2), "stems": ["a"]}]
        out = list(prefetch_to_device(iter(items), depth=2))
        assert out[0]["x"] is None and out[1]["stems"] == ["a"]
