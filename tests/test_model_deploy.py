"""Deploying the distilled student through the batch drivers (--model).

The point of distillation is replacing the classical pipeline's expensive
stages at deployment; these tests close that loop: train a small student on
a phantom cohort, write the orbax checkpoint, and run BOTH batch drivers
with --model, asserting the export contract holds and the student's masks
land where the teacher's do.
"""

import numpy as np
import pytest

pytestmark = [pytest.mark.slow]


from nm03_capstone_project_tpu.cli.runner import CohortProcessor
from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

CFG = PipelineConfig(canvas=64, render_size=64, min_dim=32)


@pytest.fixture(scope="module")
def cohort(tmp_path_factory):
    root = tmp_path_factory.mktemp("deploy_cohort")
    write_synthetic_cohort(root, n_patients=2, n_slices=4, height=64, width=60)
    return root


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory, cohort):
    """A quickly trained student checkpoint over the same cohort."""
    import jax

    from nm03_capstone_project_tpu.cli.runner import decode_and_guard
    from nm03_capstone_project_tpu.data.discovery import (
        find_patient_dirs,
        load_dicom_files_for_patient,
    )
    from nm03_capstone_project_tpu.models import (
        distill_batch,
        fit,
        init_unet,
        prepare_student_inputs,
    )
    from nm03_capstone_project_tpu.models.checkpoint import save_params

    pixels, dims = [], []
    for pid in find_patient_dirs(cohort):
        for f in load_dicom_files_for_patient(cohort, pid):
            px = decode_and_guard(f, CFG)
            canvas = np.zeros((CFG.canvas, CFG.canvas), np.float32)
            canvas[: px.shape[0], : px.shape[1]] = px
            pixels.append(canvas)
            dims.append(px.shape)
    px = np.stack(pixels)
    dm = np.asarray(dims, np.int32)
    labels = distill_batch(px, dm, CFG)
    x = prepare_student_inputs(px, CFG)
    params = init_unet(jax.random.PRNGKey(0), base=8)
    params, losses = fit(params, x, labels, dm, steps=200, lr=3e-3)
    assert losses[-1] < losses[0]
    ckpt = tmp_path_factory.mktemp("ckpt") / "checkpoint"
    save_params(ckpt, params, meta={"canvas": CFG.canvas, "model_3d": False})
    return ckpt


def _load(ckpt):
    from nm03_capstone_project_tpu.models.checkpoint import load_params

    params, _ = load_params(ckpt)
    return params


@pytest.mark.parametrize("mode", ["sequential", "parallel"])
def test_driver_deploys_student(cohort, checkpoint, tmp_path, mode):
    proc = CohortProcessor(
        cohort,
        tmp_path / mode,
        cfg=CFG,
        batch_cfg=BatchConfig(batch_size=3, io_workers=2),
        mode=mode,
        model_params=_load(checkpoint),
    )
    summary = proc.process_all_patients()
    assert summary.succeeded_slices == 8
    jpgs = list((tmp_path / mode).rglob("*.jpg"))
    assert len(jpgs) == 16  # the full pair-export contract, student compute


def test_volume_driver_deploys_3d_student(tmp_path):
    """nm03-volume --model runs the 3D student end-to-end (contract only —
    3D learning quality is covered by the train CLI tests)."""
    import jax

    from nm03_capstone_project_tpu.cli import volume as volume_cli
    from nm03_capstone_project_tpu.models import init_unet3d
    from nm03_capstone_project_tpu.models.checkpoint import save_params

    ckpt = tmp_path / "ckpt3d"
    params = init_unet3d(jax.random.PRNGKey(1), base=8)
    save_params(
        ckpt,
        params,
        meta={"canvas": 64, "model_3d": True, "norm": [0.5, 2.5, 0.0, 10000.0],
              "clip": [0.68, 4000.0]},
    )
    out = tmp_path / "out"
    rc = volume_cli.main([
        "--synthetic", "2", "--synthetic-slices", "4",
        "--canvas", "64", "--min-dim", "32", "--render-size", "64",
        "--model", str(ckpt), "--output", str(out),
    ])
    assert rc == 0
    assert len(list((out / "PGBM-0001").glob("*.jpg"))) == 8

    # the 2D/3D checkpoint cross-check refuses the wrong driver
    with pytest.raises(SystemExit, match="3D"):
        from nm03_capstone_project_tpu.cli import parallel

        parallel.main([
            "--synthetic", "1", "--canvas", "64", "--min-dim", "32",
            "--model", str(ckpt), "--output", str(tmp_path / "o2"),
        ])


@pytest.mark.parametrize("mode", ["sequential", "parallel"])
def test_mask_sink_sees_every_slice(cohort, tmp_path, mode):
    """The runner's metrics hook fires once per successful slice with the
    exact mask the driver exports (scripts/student_eval.py's foundation)."""
    import threading

    got = {}
    lock = threading.Lock()

    def sink(pid, stem, mask):
        with lock:
            got[(pid, stem)] = np.asarray(mask)

    proc = CohortProcessor(
        cohort,
        tmp_path / mode,
        cfg=CFG,
        batch_cfg=BatchConfig(batch_size=3, io_workers=2),
        mode=mode,
        mask_sink=sink,
    )
    summary = proc.process_all_patients()
    assert len(got) == summary.succeeded_slices == 8
    for (pid, stem), mask in got.items():
        assert pid.startswith("PGBM-")
        assert mask.shape == (CFG.canvas, CFG.canvas)
        assert mask.dtype == np.uint8


def test_student_masks_overlap_teacher(cohort, checkpoint, tmp_path):
    """The deployed student finds the lesions the teacher finds (IoU, not
    bit-equality — it is a learned approximation)."""
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.cli.runner import (
        _compiled_batch_mask_fn,
        _student_batch_mask,
        decode_and_guard,
    )
    from nm03_capstone_project_tpu.data.discovery import (
        find_patient_dirs,
        load_dicom_files_for_patient,
    )

    pid = find_patient_dirs(cohort)[0]
    slices = []
    for f in load_dicom_files_for_patient(cohort, pid):
        px = decode_and_guard(f, CFG)
        canvas = np.zeros((CFG.canvas, CFG.canvas), np.float32)
        canvas[: px.shape[0], : px.shape[1]] = px
        slices.append((canvas, px.shape))
    px = jnp.asarray(np.stack([c for c, _ in slices]))
    dm = jnp.asarray(np.asarray([s for _, s in slices], np.int32))
    # student first: the teacher fn DONATES its pixel argument, so it must
    # be px's last use (donation is honored on TPU/GPU)
    student = np.asarray(
        _student_batch_mask(_load(checkpoint), px, dm, CFG)
    ).astype(bool)
    teacher_mask, _conv = _compiled_batch_mask_fn(CFG)(px, dm)
    teacher = np.asarray(teacher_mask).astype(bool)
    union = (teacher | student).sum()
    assert union > 0
    iou = (teacher & student).sum() / union
    assert iou > 0.5, f"student-vs-teacher IoU {iou:.3f}"
