"""Shared NumPy/SciPy oracles (not a test module — safe to import from any
test file without creating a duplicate module instance)."""

import numpy as np
import scipy.ndimage as ndimage


def region_grow_oracle(volume, seeds, low, high, connectivity=None):
    """Connected components of the band that contain a seed.

    The one home of the seeded flood-fill oracle. ``connectivity`` defaults
    to one-step (4-connected in 2D, 6-connected in 3D); pass 26 for the
    full 3D cube.
    """
    band = (volume >= low) & (volume <= high)
    if connectivity == 26:
        structure = ndimage.generate_binary_structure(3, 3)
    else:
        structure = ndimage.generate_binary_structure(volume.ndim, 1)
    labels, _ = ndimage.label(band, structure=structure)
    hit = np.unique(labels[seeds & band])
    hit = hit[hit != 0]
    return np.isin(labels, hit).astype(np.uint8)
