"""nm03-lint tests: one fixture battery per rule family, the import-contract
monkeypatch drill, the acceptance break-drills against the REAL tree, the
CLI/JSON surface, the check_static gate subprocess, and the --sanitize
runtime twins.

Fixture trees are built under tmp_path with the same relative layout the
path-scoped rules key on (serving/, ops/, supervisor.py), so a snippet
exercises exactly the rule its real counterpart would.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

from nm03_capstone_project_tpu.analysis import ALL_RULES, collect_files, run_rules
from nm03_capstone_project_tpu.analysis.atomicio import (
    check_atomic_io,
    check_obs_dump_io,
)
from nm03_capstone_project_tpu.analysis.cachekey import check_cache_key
from nm03_capstone_project_tpu.analysis.compilehome import check_compile_home
from nm03_capstone_project_tpu.analysis.contracts import check_import_contracts
from nm03_capstone_project_tpu.analysis.core import (
    apply_baseline,
    load_baseline,
    write_baseline,
)
from nm03_capstone_project_tpu.analysis.dtypes import check_dtype_discipline
from nm03_capstone_project_tpu.analysis.hostsync import check_host_sync
from nm03_capstone_project_tpu.analysis.lockorder import (
    build_lock_graph,
    check_lock_order,
    explain_witness,
)
from nm03_capstone_project_tpu.analysis.metricsdocs import check_metrics_docs
from nm03_capstone_project_tpu.analysis.retrace import check_retrace
from nm03_capstone_project_tpu.analysis.staginghome import check_staging_home
from nm03_capstone_project_tpu.analysis.threads import check_thread_shared_state

REPO = pathlib.Path(__file__).resolve().parents[1]
PKG = "nm03_capstone_project_tpu"


def lint_tree(tmp_path, files, rules=ALL_RULES, select=None):
    """Write {relpath: source} under tmp_path and lint it as a root."""
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    parsed = collect_files([tmp_path], tmp_path)
    return run_rules(parsed, rules, select=select)


def rules_of(findings):
    return sorted(f.rule for f in findings)


class TestImportContract:
    def test_direct_violation(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/resilience/policy.py": "import jax\n"},
            rules=(check_import_contracts,),
        )
        assert "NM301" in rules_of(fs)

    def test_transitive_violation(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/resilience/policy.py": "import threading\n",
                f"{PKG}/resilience/helper.py": "import numpy as np\n",
                f"{PKG}/resilience/supervisor.py": (
                    f"from {PKG}.resilience.helper import np\n"
                ),
            },
            rules=(check_import_contracts,),
        )
        nm301 = [f for f in fs if f.rule == "NM301"]
        assert nm301, fs
        assert any("via" in f.message for f in nm301)

    def test_lazy_import_is_sanctioned(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/resilience/policy.py": """
                def fn():
                    import jax
                    return jax
                """
            },
            rules=(check_import_contracts,),
        )
        assert "NM301" not in rules_of(fs)

    def test_type_checking_guard_exempt(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/resilience/policy.py": """
                from typing import TYPE_CHECKING
                if TYPE_CHECKING:
                    import jax
                """
            },
            rules=(check_import_contracts,),
        )
        assert "NM301" not in rules_of(fs)

    def test_relative_import_from_package_init_resolves(self, tmp_path):
        """'from .events import X' in a contract package's __init__.py must
        resolve against the package itself, not its parent — the NM301
        edge would otherwise silently vanish from the graph."""
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/__init__.py": "from .events import EventLog\n",
                f"{PKG}/obs/events.py": "import jax\n",
            },
            rules=(check_import_contracts,),
        )
        msgs = [f.message for f in fs if f.rule == "NM301"]
        # the direct events.py violation AND the one reached via __init__
        assert any("obs.events" in m and "via" not in m for m in msgs), msgs
        assert any(f"{PKG}.obs " in m or f"{PKG}.obs is" in m for m in msgs), msgs

    def test_ancestor_init_joins_the_graph(self, tmp_path):
        """Importing pkg.sub.mod executes pkg/__init__ and pkg/sub/__init__
        on the way down — a banned import hidden in an ancestor __init__ is
        the same import-time cost and must be caught."""
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/resilience/policy.py": (
                    f"from {PKG}.helpers.tools import x\n"
                ),
                f"{PKG}/helpers/__init__.py": "import jax\n",
                f"{PKG}/helpers/tools.py": "x = 1\n",
            },
            rules=(check_import_contracts,),
        )
        assert "NM301" in rules_of(fs), [f.render() for f in fs]

    def test_monkeypatched_jax_import_fails_real_module(self, tmp_path):
        """The acceptance drill: copy the REAL policy.py, inject one jax
        import, and the contract must fail with NM301."""
        src = (REPO / PKG / "resilience" / "policy.py").read_text()
        assert "\nimport jax" not in src  # the real module honors its contract
        broken = src.replace(
            "import dataclasses", "import dataclasses\nimport jax", 1
        )
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/resilience/policy.py": broken},
            rules=(check_import_contracts,),
        )
        assert "NM301" in rules_of(fs)

    def test_real_tree_is_clean(self):
        parsed = collect_files(
            [REPO / PKG, REPO / "bench.py", REPO / "scripts"], REPO
        )
        fs = run_rules(parsed, (check_import_contracts,))
        assert rules_of(fs) == [], [f.render() for f in fs]


class TestRetrace:
    def test_array_ctor_in_jitted_body(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import jax, jax.numpy as jnp
                @jax.jit
                def f(x):
                    return x + jnp.asarray([1, 2, 3])
                """
            },
            rules=(check_retrace,),
        )
        assert "NM311" in rules_of(fs)

    def test_assigned_jit_resolves_local_def(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import jax, jax.numpy as jnp
                def g(x):
                    return jnp.array(x.tolist())
                f = jax.jit(jax.vmap(g))
                """
            },
            rules=(check_retrace,),
        )
        assert "NM311" in rules_of(fs)

    def test_scalar_literal_call_without_static(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import jax
                f = jax.jit(lambda x, n: x * n)
                out = f(arr, 3)
                """
            },
            rules=(check_retrace,),
        )
        assert "NM312" in rules_of(fs)

    def test_static_argnames_is_negative(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import jax
                f = jax.jit(lambda x, n: x * n, static_argnames=("n",))
                out = f(arr, 3)
                """
            },
            rules=(check_retrace,),
        )
        assert rules_of(fs) == []

    def test_suppression_with_reason(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import jax, jax.numpy as jnp
                @jax.jit
                def f(x):
                    # nm03-lint: disable=NM311 constant folded deliberately
                    return x + jnp.asarray([1, 2, 3])
                """
            },
            rules=(check_retrace,),
        )
        assert rules_of(fs) == []

    def test_suppression_without_reason_is_nm390(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import jax, jax.numpy as jnp
                @jax.jit
                def f(x):
                    return x + jnp.asarray([1, 2])  # nm03-lint: disable=NM311
                """
            },
            rules=(check_retrace,),
        )
        assert rules_of(fs) == ["NM390"]


class TestHostSync:
    def test_item_in_span_body(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                def run(timer, x):
                    with timer.span("compute"):
                        v = x.item()
                    return v
                """
            },
            rules=(check_host_sync,),
        )
        assert "NM321" in rules_of(fs)

    def test_nested_def_in_span_not_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                import numpy as np
                def run(timer, fn, x):
                    with timer.span("dispatch"):
                        def primary():
                            return np.asarray(fn(x))
                        out = launch(primary)
                    return out
                """
            },
            rules=(check_host_sync,),
        )
        assert rules_of(fs) == []

    def test_dispatch_path_scope(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/batcher.py": """
                import numpy as np
                class DynamicBatcher:
                    def execute(self, reqs):
                        return np.asarray(reqs[0].mask_dev)
                    def unscoped(self, x):
                        return np.asarray(x)
                """
            },
            rules=(check_host_sync,),
        )
        assert rules_of(fs) == ["NM322"]  # only the registered function

    def test_shape_access_is_host_metadata(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                "mod.py": """
                def run(timer, x):
                    with timer.span("compute"):
                        n = int(x.shape[0])
                    return n
                """
            },
            rules=(check_host_sync,),
        )
        assert rules_of(fs) == []


class TestThreadSharedState:
    CLASS_TMPL = """
    import threading
    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._thread = threading.Thread(target=self._run)
        def _run(self):
            {write}
    """

    def test_unguarded_write_flagged(self, tmp_path):
        src = textwrap.dedent(self.CLASS_TMPL).format(write="self.count += 1")
        fs = lint_tree(
            tmp_path, {f"{PKG}/serving/w.py": src}, rules=(check_thread_shared_state,)
        )
        assert "NM331" in rules_of(fs)

    def test_guarded_write_clean(self, tmp_path):
        src = textwrap.dedent(self.CLASS_TMPL).format(
            write="with self._lock:\n                self.count += 1"
        )
        fs = lint_tree(
            tmp_path, {f"{PKG}/serving/w.py": src}, rules=(check_thread_shared_state,)
        )
        assert rules_of(fs) == []

    def test_container_mutation_behind_attr_flagged(self, tmp_path):
        src = textwrap.dedent(
            """
            import threading
            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.stats = {"n": 0}
                def bump(self):
                    self.stats["n"] += 1
            """
        )
        fs = lint_tree(
            tmp_path, {f"{PKG}/serving/w.py": src}, rules=(check_thread_shared_state,)
        )
        assert "NM331" in rules_of(fs)

    def test_sync_typed_attr_exempt(self, tmp_path):
        src = textwrap.dedent(
            """
            import threading
            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.done = threading.Event()
                def finish(self):
                    self.done = threading.Event()
            """
        )
        fs = lint_tree(
            tmp_path, {f"{PKG}/serving/w.py": src}, rules=(check_thread_shared_state,)
        )
        assert rules_of(fs) == []

    def test_out_of_scope_file_ignored(self, tmp_path):
        src = textwrap.dedent(self.CLASS_TMPL).format(write="self.count += 1")
        fs = lint_tree(
            tmp_path, {f"{PKG}/data/w.py": src}, rules=(check_thread_shared_state,)
        )
        assert rules_of(fs) == []

    def test_removing_a_lock_in_real_batcher_fails(self, tmp_path):
        """The acceptance drill: the REAL batcher minus its stats lock must
        fail NM331."""
        src = (REPO / PKG / "serving" / "batcher.py").read_text()
        guarded = (
            '        with self._lock:\n'
            '            self._stats["batches"] += len(chunks)'
        )
        assert guarded in src
        broken = src.replace(
            guarded,
            '        if True:\n'
            '            self._stats["batches"] += len(chunks)',
            1,
        )
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/serving/batcher.py": broken},
            rules=(check_thread_shared_state,),
        )
        assert "NM331" in rules_of(fs)

    def test_real_batcher_is_clean(self, tmp_path):
        src = (REPO / PKG / "serving" / "batcher.py").read_text()
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/serving/batcher.py": src},
            rules=(check_thread_shared_state,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_removing_the_lane_state_lock_fails(self, tmp_path):
        """ISSUE 8 CI satellite: NM331 covers the lane fault-domain state
        machine — the REAL serving/lanes.py with a quarantine transition
        moved outside its lock must be a lint finding, not a race found
        in production."""
        src = (REPO / PKG / "serving" / "lanes.py").read_text()
        guarded = (
            "        with self._lock:\n"
            "            if self._states[lane] != QUARANTINED:\n"
            "                return False\n"
            "            self._states[lane] = PROBATION"
        )
        assert guarded in src  # begin_probation's guarded transition
        broken = src.replace(
            guarded,
            "        if True:\n"
            "            if self._states[lane] != QUARANTINED:\n"
            "                return False\n"
            "            self._states[lane] = PROBATION",
            1,
        )
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/serving/lanes.py": broken},
            rules=(check_thread_shared_state,),
        )
        assert "NM331" in rules_of(fs)

    def test_real_lane_state_machine_is_clean(self, tmp_path):
        src = (REPO / PKG / "serving" / "lanes.py").read_text()
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/serving/lanes.py": src},
            rules=(check_thread_shared_state,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_removing_the_saturation_lock_fails(self, tmp_path):
        """ISSUE 10 satellite: NM331's scope covers obs/saturation.py —
        the REAL sliding-window monitor with its lane-table write moved
        outside the lock must be a lint finding."""
        src = (REPO / PKG / "obs" / "saturation.py").read_text()
        guarded = (
            "        with self._lock:\n"
            "            self._lanes = rows"
        )
        assert guarded in src  # set_lanes' guarded fleet-table write
        broken = src.replace(
            guarded,
            "        if True:\n"
            "            self._lanes = rows",
            1,
        )
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/obs/saturation.py": broken},
            rules=(check_thread_shared_state,),
        )
        assert "NM331" in rules_of(fs)

    def test_real_saturation_monitor_is_clean(self, tmp_path):
        src = (REPO / PKG / "obs" / "saturation.py").read_text()
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/obs/saturation.py": src},
            rules=(check_thread_shared_state,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_removing_the_replica_health_table_lock_fails(self, tmp_path):
        """ISSUE 13 satellite: NM331's scope covers the fleet router's
        health table — the REAL fleet/replicas.py with the signal-table
        write moved outside its lock must be a lint finding (the table
        is written by the health poller and read by every routing pick
        and /readyz render)."""
        src = (REPO / PKG / "fleet" / "replicas.py").read_text()
        guarded = (
            "        with self._lock:\n"
            "            if target not in self._signals:\n"
            '                raise KeyError(f"unknown replica target '
            '{target!r}")\n'
            "            self._signals[target] = sig"
        )
        assert guarded in src  # update_signals' guarded table write
        broken = src.replace(
            guarded,
            "        if True:\n"
            "            if target not in self._signals:\n"
            '                raise KeyError(f"unknown replica target '
            '{target!r}")\n'
            "            self._signals[target] = sig",
            1,
        )
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/fleet/replicas.py": broken},
            rules=(check_thread_shared_state,),
        )
        assert "NM331" in rules_of(fs)

    def test_real_fleet_modules_are_clean(self, tmp_path):
        for mod in ("replicas.py", "router.py", "manager.py"):
            src = (REPO / PKG / "fleet" / mod).read_text()
            fs = lint_tree(
                tmp_path,
                {f"{PKG}/fleet/{mod}": src},
                rules=(check_thread_shared_state,),
            )
            assert rules_of(fs) == [], [f.render() for f in fs]

    def test_fleet_package_is_contract_registered(self, tmp_path):
        """ISSUE 13: the fleet package is NM301-pinned jax- AND
        numpy-free — a backend import smuggled into the router must be a
        lint finding, not a compile-hub claim paid by a byte-shuffler."""
        from nm03_capstone_project_tpu.analysis.contracts import (
            CONTRACT_REGISTRY,
        )

        assert CONTRACT_REGISTRY[f"{PKG}.fleet"] == ("jax", "numpy")
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/fleet/router.py": "import numpy\n"},
            rules=(check_import_contracts,),
        )
        assert "NM301" in rules_of(fs)


class TestDtypeDiscipline:
    def test_float64_dtype_flagged_in_ops(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/k.py": """
                import numpy as np
                xs = np.arange(8, dtype=np.float64)
                """
            },
            rules=(check_dtype_discipline,),
        )
        assert "NM341" in rules_of(fs)

    def test_python_float_dtype_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/k.py": """
                import numpy as np
                def f(x):
                    return x.astype(float)
                """
            },
            rules=(check_dtype_discipline,),
        )
        assert "NM341" in rules_of(fs)

    def test_f32_is_negative(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/k.py": """
                import numpy as np
                xs = np.arange(8, dtype=np.float32)
                """
            },
            rules=(check_dtype_discipline,),
        )
        assert rules_of(fs) == []

    def test_out_of_range_u8_compare(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/k.py": """
                import jax.numpy as jnp
                def f(x):
                    return x.astype(jnp.uint8) > 300
                """
            },
            rules=(check_dtype_discipline,),
        )
        assert "NM342" in rules_of(fs)

    def test_outside_ops_not_scoped(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/data/k.py": """
                import numpy as np
                xs = np.arange(8, dtype=np.float64)
                """
            },
            rules=(check_dtype_discipline,),
        )
        assert rules_of(fs) == []


class TestAtomicIo:
    def test_plain_write_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/io.py": """
                import json
                def dump(path, payload):
                    with open(path, "w") as f:
                        json.dump(payload, f)
                """
            },
            rules=(check_atomic_io,),
        )
        assert "NM351" in rules_of(fs)

    def test_tmp_rename_idiom_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/io.py": """
                import json, os
                def dump(path, payload):
                    tmp = f"{path}.tmp"
                    with open(tmp, "w") as f:
                        json.dump(payload, f)
                    os.replace(tmp, path)
                """
            },
            rules=(check_atomic_io,),
        )
        assert rules_of(fs) == []

    def test_append_mode_exempt(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/io.py": """
                def journal(path, line):
                    with open(path, "a") as f:
                        f.write(line)
                """
            },
            rules=(check_atomic_io,),
        )
        assert rules_of(fs) == []

    def test_str_replace_does_not_count_as_rename(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/io.py": """
                def dump(path, payload):
                    path = path.replace("-", "_")
                    with open(path, "w") as f:
                        f.write(payload)
                """
            },
            rules=(check_atomic_io,),
        )
        assert "NM351" in rules_of(fs)

    def test_real_tree_atomic_clean(self):
        parsed = collect_files([REPO / PKG, REPO / "scripts"], REPO)
        fs = run_rules(parsed, (check_atomic_io,))
        assert rules_of(fs) == [], [f.render() for f in fs]


class TestObsDumpIo:
    """NM371 (ISSUE 7): the flight-recorder/trace modules' write discipline
    is stricter than NM351 — every write routes through atomic_write_*."""

    def test_direct_write_in_flightrec_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/flightrec.py": """
                import json
                def dump(path, snap):
                    with open(path, "w") as f:
                        json.dump(snap, f)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert "NM371" in rules_of(fs)

    def test_path_open_write_flagged(self, tmp_path):
        # Path.open("w")/io.open are the same primitive wearing an
        # attribute; mode is the FIRST positional there
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/flightrec.py": """
                import json, pathlib
                def dump(path, snap):
                    with pathlib.Path(path).open("w") as f:
                        json.dump(snap, f)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert "NM371" in rules_of(fs)

    def test_io_open_literal_path_write_flagged(self, tmp_path):
        # io.open takes (path, mode): a literal path must not masquerade
        # as a read mode and let a write through
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/flightrec.py": """
                import io, json
                def dump(snap):
                    with io.open("debug.json", "w") as f:
                        json.dump(snap, f)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert "NM371" in rules_of(fs)

    def test_io_open_literal_path_read_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/trace.py": """
                import io, json
                def load():
                    with io.open("events.jsonl") as f:
                        return json.load(f)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_path_open_read_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/trace.py": """
                import json, pathlib
                def load(path):
                    with pathlib.Path(path).open() as f:
                        return json.load(f)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_hand_rolled_tmp_rename_flagged_too(self, tmp_path):
        # NM351 would ACCEPT this; NM371 must not — the idiom's single
        # point of correctness is utils.atomicio, not a local copy
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/trace.py": """
                import json, os
                def export(path, payload):
                    tmp = f"{path}.tmp"
                    with open(tmp, "w") as f:
                        json.dump(payload, f)
                    os.replace(tmp, path)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert "NM371" in rules_of(fs)

    def test_from_import_replace_flagged(self, tmp_path):
        # ANY spelling: `from os import replace` must not slip past a
        # matcher pinned to the literal `os.replace` attribute form
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/flightrec.py": """
                import json
                from os import replace as publish
                def export(path, payload):
                    tmp = f"{path}.tmp"
                    with open(tmp, "x") as f:
                        json.dump(payload, f)
                    publish(tmp, path)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs).count("NM371") >= 2  # the open AND the rename

    def test_aliased_module_rename_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/trace.py": """
                import os as _os
                def export(tmp, path):
                    _os.rename(tmp, path)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert "NM371" in rules_of(fs)

    def test_pathlib_replace_and_rename_flagged(self, tmp_path):
        # the modern spelling of the banned tmp+rename two-step
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/flightrec.py": """
                import pathlib
                def publish(tmp, path):
                    pathlib.Path(tmp).replace(path)
                def publish2(tmp, path):
                    tmp.rename(path)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs).count("NM371") == 2

    def test_str_replace_clean(self, tmp_path):
        # str.replace takes two positionals — must not trip the
        # one-positional pathlib-replace matcher
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/trace.py": """
                def safe(reason):
                    return reason.replace(" ", "_")
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_atomic_write_and_reads_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/flightrec.py": f"""
                import json
                from {PKG}.utils.atomicio import atomic_write_text
                def load(path):
                    with open(path) as f:
                        return json.load(f)
                def dump(path, snap):
                    atomic_write_text(path, json.dumps(snap))
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_other_modules_unaffected(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/obs/events.py": """
                def sink(path):
                    return open(path, "w", buffering=1)
                """
            },
            rules=(check_obs_dump_io,),
        )
        assert rules_of(fs) == []

    def test_trace_flightrec_pinned_in_contract_registry(self):
        from nm03_capstone_project_tpu.analysis.contracts import (
            CONTRACT_REGISTRY,
        )

        for mod in (f"{PKG}.obs.trace", f"{PKG}.obs.flightrec"):
            assert CONTRACT_REGISTRY[mod] == ("jax", "numpy")

    def test_real_tree_obs_dump_clean(self):
        parsed = collect_files([REPO / PKG], REPO)
        fs = run_rules(parsed, (check_obs_dump_io,))
        assert rules_of(fs) == [], [f.render() for f in fs]


class TestCompileHome:
    def test_direct_jit_reference_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/thing.py": """
                import jax
                f = jax.jit(lambda x: x)
                """
            },
            rules=(check_compile_home,),
        )
        assert "NM361" in rules_of(fs)

    def test_import_binding_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/parallel/z.py": """
                from jax.experimental.shard_map import shard_map
                """
            },
            rules=(check_compile_home,),
        )
        assert "NM361" in rules_of(fs)

    def test_aliased_module_attribute_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/parallel/z.py": """
                import jax.experimental.shard_map as sm
                g = sm.shard_map
                """
            },
            rules=(check_compile_home,),
        )
        assert "NM361" in rules_of(fs)

    def test_partial_decorator_arg_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/k.py": """
                import functools
                import jax
                @functools.partial(jax.jit, static_argnames=("n",))
                def f(x, n):
                    return x * n
                """
            },
            rules=(check_compile_home,),
        )
        assert "NM361" in rules_of(fs)

    def test_compilehub_is_the_sanctioned_home(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/compat.py": """
                import jax
                from jax.experimental.shard_map import shard_map
                p = jax.jit
                """
            },
            rules=(check_compile_home,),
        )
        assert rules_of(fs) == []

    def test_hub_consumers_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/parallel/z.py": f"""
                import jax
                from {PKG}.compilehub import hub_jit, shard_map
                f = hub_jit(jax.vmap(lambda x: x))
                g = shard_map
                """
            },
            rules=(check_compile_home,),
        )
        assert rules_of(fs) == []

    def test_suppression_with_reason_honored(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ops/k.py": """
                import jax
                # nm03-lint: disable=NM361 Pallas kernel wrapper: the jit is the kernel's dispatch envelope
                f = jax.jit(lambda x: x)
                """
            },
            rules=(check_compile_home,),
        )
        assert rules_of(fs) == []

    def test_real_tree_compile_home_clean(self):
        """The acceptance bar: zero NM361 findings outside compilehub/ on
        the real tree (the Pallas wrappers' reasoned suppressions are the
        only sanctioned escapes)."""
        parsed = collect_files(
            [REPO / PKG, REPO / "bench.py", REPO / "scripts"], REPO
        )
        fs = run_rules(parsed, (check_compile_home,))
        assert rules_of(fs) == [], [f.render() for f in fs]


class TestStagingHome:
    def test_direct_device_put_reference_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/cli/thing.py": """
                import jax
                x = jax.device_put([1, 2, 3])
                """
            },
            rules=(check_staging_home,),
        )
        assert "NM401" in rules_of(fs)

    def test_import_binding_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/cli/thing.py": """
                from jax import device_put
                """
            },
            rules=(check_staging_home,),
        )
        assert "NM401" in rules_of(fs)

    def test_aliased_module_attribute_flagged(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/cli/thing.py": """
                import jax as j
                stage = j.device_put
                """
            },
            rules=(check_staging_home,),
        )
        assert "NM401" in rules_of(fs)

    def test_ingest_is_the_sanctioned_home(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/ingest/staging.py": """
                import jax
                def stage(x):
                    return jax.device_put(x)
                """
            },
            rules=(check_staging_home,),
        )
        assert rules_of(fs) == []

    def test_compilehub_and_sanitize_exempt(self, tmp_path):
        # warmup staging is the hub's own job; the sanitize runtime twin
        # documents the sanctioned idiom — both are reasoned exemptions
        # named by the rule itself, not suppressions
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/hub.py": """
                import jax
                canary = jax.device_put(0)
                """,
                f"{PKG}/utils/sanitize.py": """
                import jax
                probe = jax.device_put(1)
                """,
            },
            rules=(check_staging_home,),
        )
        assert rules_of(fs) == []

    def test_ingest_consumers_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/cli/thing.py": f"""
                from {PKG}.ingest import stage_batch
                out = stage_batch({{"pixels": None}})
                """
            },
            rules=(check_staging_home,),
        )
        assert rules_of(fs) == []

    def test_suppression_with_reason_honored(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/cli/thing.py": """
                import jax
                p = jax.device_put(0)  # nm03-lint: disable=NM401 one-time model-weight placement, not the batch data path
                """
            },
            rules=(check_staging_home,),
        )
        assert rules_of(fs) == []

    def test_real_tree_staging_home_clean(self):
        """The acceptance bar: zero NM401 findings outside ingest/ on the
        real tree (the CPU-fallback, parameter-placement and bench
        measurement suppressions are the only sanctioned escapes)."""
        parsed = collect_files(
            [REPO / PKG, REPO / "bench.py", REPO / "scripts"], REPO
        )
        fs = run_rules(parsed, (check_staging_home,))
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_break_drill_stripped_suppression_trips(self, tmp_path):
        """Break drill: the real runner.py with its NM401 suppressions
        stripped must fail the rule — proving the real tree is clean
        BECAUSE of the reasoned suppressions, not because the rule is
        blind to the drivers."""
        src = (REPO / PKG / "cli" / "runner.py").read_text()
        assert "disable=NM401" in src
        stripped = "\n".join(
            line.split("# nm03-lint: disable=NM401")[0].rstrip()
            if "disable=NM401" in line and line.strip().startswith("#") is False
            else ("" if "disable=NM401" in line else line)
            for line in src.splitlines()
        )
        tree = tmp_path / PKG / "cli"
        tree.mkdir(parents=True)
        (tree / "runner.py").write_text(stripped)
        parsed = collect_files([tmp_path / PKG], tmp_path)
        fs = run_rules(parsed, (check_staging_home,))
        assert "NM401" in rules_of(fs), "stripping the suppressions must trip NM401"


class TestCacheKey:
    """NM381 (ISSUE 9): cache-key completeness — every CompileSpec field
    must be consumed by the sibling persist.py's key derivation, or two
    different programs could share one on-disk executable."""

    GOOD_HUB = f"""
    import dataclasses
    @dataclasses.dataclass(frozen=True)
    class CompileSpec:
        name: str
        cfg: object = None
        shape: tuple = None
    """

    def test_missing_field_flagged_at_its_declaration(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/hub.py": self.GOOD_HUB,
                f"{PKG}/compilehub/persist.py": """
                def from_spec(spec):
                    return (spec.name, spec.shape)  # cfg never read
                """,
            },
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == ["NM381"]
        assert "cfg" in fs[0].message and fs[0].path.endswith("hub.py")

    def test_full_coverage_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/hub.py": self.GOOD_HUB,
                f"{PKG}/compilehub/persist.py": """
                def digest(spec):
                    return hash(spec.cfg)
                def from_spec(spec):
                    return (spec.name, spec.shape, digest(spec))
                """,
            },
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == []

    def test_tree_without_persist_module_is_out_of_scope(self, tmp_path):
        # fixture trees for other rule families carry hub-less layouts;
        # the completeness contract only binds where the persistent layer
        # exists next to the spec
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/compilehub/hub.py": self.GOOD_HUB},
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == []

    def test_hub_without_compile_spec_is_out_of_scope(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/hub.py": "class Other:\n    pass\n",
                f"{PKG}/compilehub/persist.py": "def from_spec(spec): ...\n",
            },
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == []

    def test_suppression_with_reason_honored(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/hub.py": """
                import dataclasses
                @dataclasses.dataclass(frozen=True)
                class CompileSpec:
                    name: str
                    # nm03-lint: disable=NM381 display-only field, never affects the compiled program
                    color: str = ""
                """,
                f"{PKG}/compilehub/persist.py": """
                def from_spec(spec):
                    return (spec.name,)
                """,
            },
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == []

    def test_real_tree_clean_and_break_drill(self, tmp_path):
        """Acceptance: the REAL hub/persist pair passes NM381, and the
        same pair with one spec read stripped from persist.py fails —
        the rule is wired to the actual contract, not a fixture echo."""
        hub_src = (REPO / PKG / "compilehub" / "hub.py").read_text()
        persist_src = (REPO / PKG / "compilehub" / "persist.py").read_text()
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/compilehub/hub.py": hub_src,
                f"{PKG}/compilehub/persist.py": persist_src,
            },
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]
        broken = persist_src.replace("donate=bool(spec.donate),", "")
        assert broken != persist_src, "break-drill anchor moved in persist.py"
        (tmp_path / "broken").mkdir()
        fs = lint_tree(
            tmp_path / "broken",
            {
                f"{PKG}/compilehub/hub.py": hub_src,
                f"{PKG}/compilehub/persist.py": broken,
            },
            rules=(check_cache_key,),
        )
        assert rules_of(fs) == ["NM381"]
        assert "donate" in fs[0].message


class TestMetricsDocs:
    """NM392 (ISSUE 10): metrics↔docs drift — every metric-name constant
    in serving/metrics.py / obs/metrics.py has a docs/OBSERVABILITY.md
    table row and vice versa."""

    DOC = """
    # Observability
    | name | type | labels | meaning |
    |---|---|---|---|
    | `serving_foo_total` | counter | — | foos served |
    """

    def test_undocumented_constant_flagged_at_declaration(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/metrics.py": """
                SERVING_FOO_TOTAL = "serving_foo_total"
                SERVING_BAR = "serving_bar_ratio"
                """,
                "docs/OBSERVABILITY.md": self.DOC,
            },
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == ["NM392"]
        assert "serving_bar_ratio" in fs[0].message
        assert fs[0].path.endswith("serving/metrics.py")

    def test_stale_docs_row_flagged_at_docs_line(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/metrics.py": (
                    'SERVING_FOO_TOTAL = "serving_foo_total"\n'
                ),
                "docs/OBSERVABILITY.md": self.DOC + (
                    "    | `serving_gone_total` | counter | — | removed |\n"
                ),
            },
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == ["NM392"]
        assert "serving_gone_total" in fs[0].message
        assert fs[0].path == "docs/OBSERVABILITY.md"

    def test_full_agreement_clean(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/metrics.py": (
                    'SERVING_FOO_TOTAL = "serving_foo_total"\n'
                ),
                f"{PKG}/obs/metrics.py": 'OBS_GAUGE = "obs_gauge"\n',
                "docs/OBSERVABILITY.md": self.DOC + (
                    "    | `obs_gauge` | gauge | — | a gauge |\n"
                ),
            },
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_non_metric_constants_excluded(self, tmp_path):
        # schema ids (dots), lowercase names, non-strings and re-exports
        # are not metric names — none may demand a docs row
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/metrics.py": """
                from os.path import sep as SEP_REEXPORT  # not an Assign
                SCHEMA_X = "nm03.metrics.v1"
                BUCKETS = (1.0, 2.0)
                _PRIVATE = "serving_hidden_total"
                lower_case = "serving_also_hidden"
                SERVING_FOO_TOTAL = "serving_foo_total"
                """,
                "docs/OBSERVABILITY.md": self.DOC,
            },
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_missing_docs_file_is_a_finding(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/metrics.py": (
                    'SERVING_FOO_TOTAL = "serving_foo_total"\n'
                )
            },
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == ["NM392"]
        assert "no docs/OBSERVABILITY.md" in fs[0].message

    def test_other_metrics_modules_out_of_scope(self, tmp_path):
        # only serving/metrics.py and obs/metrics.py own names; a
        # data/metrics.py is not bound to the contract
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/data/metrics.py": 'X = "data_things_total"\n'},
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == []

    def test_real_tree_clean_and_break_drill(self, tmp_path):
        """Acceptance: the REAL name modules agree with the REAL docs at
        zero findings, and deleting one docs row (or adding one
        undocumented constant) fails — the gate is wired to the actual
        contract, not a fixture echo."""
        serving_src = (REPO / PKG / "serving" / "metrics.py").read_text()
        obs_src = (REPO / PKG / "obs" / "metrics.py").read_text()
        doc_src = (REPO / "docs" / "OBSERVABILITY.md").read_text()
        tree = {
            f"{PKG}/serving/metrics.py": serving_src,
            f"{PKG}/obs/metrics.py": obs_src,
            "docs/OBSERVABILITY.md": doc_src,
        }
        fs = lint_tree(tmp_path, tree, rules=(check_metrics_docs,))
        assert rules_of(fs) == [], [f.render() for f in fs]
        # drill 1: drop the serving_mfu docs row -> undocumented constant
        row = next(
            line for line in doc_src.splitlines()
            if line.startswith("| `serving_mfu` |")
        )
        (tmp_path / "drill1").mkdir()
        fs = lint_tree(
            tmp_path / "drill1",
            {**tree, "docs/OBSERVABILITY.md": doc_src.replace(row, "", 1)},
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == ["NM392"]
        assert "serving_mfu" in fs[0].message
        # drill 2: a brand-new constant with no docs row
        (tmp_path / "drill2").mkdir()
        fs = lint_tree(
            tmp_path / "drill2",
            {
                **tree,
                f"{PKG}/serving/metrics.py": serving_src
                + '\nSERVING_NEW_THING = "serving_new_thing_total"\n',
            },
            rules=(check_metrics_docs,),
        )
        assert rules_of(fs) == ["NM392"]
        assert "serving_new_thing_total" in fs[0].message
        # drill 3 (ISSUE 14): the fleet/SLO names are INSIDE the
        # contract — dropping the slo_burn_rate_fast row (or the
        # fleet_request_seconds row) must fail at the obs/metrics.py
        # constant, exactly like any serving name
        for name in ("slo_burn_rate_fast", "fleet_request_seconds"):
            row = next(
                line for line in doc_src.splitlines()
                if line.startswith(f"| `{name}` |")
            )
            d = tmp_path / f"drill3_{name}"
            d.mkdir()
            fs = lint_tree(
                d,
                {**tree, "docs/OBSERVABILITY.md": doc_src.replace(row, "", 1)},
                rules=(check_metrics_docs,),
            )
            assert rules_of(fs) == ["NM392"]
            assert name in fs[0].message
            assert fs[0].path.endswith("obs/metrics.py")


class TestBaseline:
    def test_round_trip_and_absorption(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/resilience/policy.py": "import jax\n"},
            rules=(check_import_contracts,),
        )
        assert fs
        bl_path = tmp_path / "baseline.json"
        write_baseline(bl_path, fs)
        baseline = load_baseline(bl_path)
        new, matched = apply_baseline(fs, baseline)
        assert new == [] and matched == len(fs)

    def test_new_finding_not_absorbed(self, tmp_path):
        bl_path = tmp_path / "baseline.json"
        write_baseline(bl_path, [])
        fs = lint_tree(
            tmp_path,
            {f"{PKG}/resilience/policy.py": "import jax\n"},
            rules=(check_import_contracts,),
        )
        new, matched = apply_baseline(fs, load_baseline(bl_path))
        assert len(new) == len(fs) and matched == 0

    def test_fingerprint_survives_line_drift(self, tmp_path):
        fs1 = lint_tree(
            tmp_path,
            {f"{PKG}/resilience/policy.py": "import jax\n"},
            rules=(check_import_contracts,),
        )
        fs2 = lint_tree(
            tmp_path,
            {f"{PKG}/resilience/policy.py": '"""doc."""\n\n\nimport jax\n'},
            rules=(check_import_contracts,),
        )
        assert {f.fingerprint for f in fs1} == {f.fingerprint for f in fs2}


class TestCliAndGate:
    def test_cli_json_smoke(self):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "nm03_capstone_project_tpu.analysis.cli",
                "--root",
                str(REPO),
                "--format",
                "json",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["findings"] == []
        assert payload["files_scanned"] > 50

    def test_cli_list_rules(self):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "nm03_capstone_project_tpu.analysis.cli",
                "--list-rules",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=60,
        )
        assert proc.returncode == 0
        for rid in ("NM301", "NM311", "NM321", "NM331", "NM341", "NM351"):
            assert rid in proc.stdout

    def test_cli_fails_on_fixture_violation(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        mod = tmp_path / PKG / "resilience"
        mod.mkdir(parents=True)
        (mod / "policy.py").write_text("import jax\n")
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "nm03_capstone_project_tpu.analysis.cli",
                "--root",
                str(tmp_path),
                str(tmp_path / PKG),
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=60,
        )
        assert proc.returncode == 1
        assert "NM301" in proc.stdout

    def test_check_static_gate_subprocess(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "check_static.py")],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "check_static: OK" in proc.stdout
        assert "nm03-lint: 0 new finding(s)" in proc.stdout

    def test_update_baseline_writes_and_exits_zero(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        mod = tmp_path / PKG / "resilience"
        mod.mkdir(parents=True)
        (mod / "policy.py").write_text("import jax\n")
        bl = tmp_path / "bl.json"
        args = [
            sys.executable,
            "-m",
            "nm03_capstone_project_tpu.analysis.cli",
            "--root",
            str(tmp_path),
            "--baseline",
            str(bl),
            str(tmp_path / PKG),
        ]
        proc = subprocess.run(
            args + ["--update-baseline"],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert proc.returncode == 0 and bl.exists()
        proc = subprocess.run(
            args, capture_output=True, text=True, cwd=REPO, timeout=60
        )
        assert proc.returncode == 0, proc.stdout  # baselined -> green


class TestSanitize:
    def test_watchdog_counts_and_counter(self):
        import logging

        from nm03_capstone_project_tpu.obs.metrics import MetricsRegistry
        from nm03_capstone_project_tpu.utils.sanitize import (
            RECOMPILES_TOTAL,
            RecompileWatchdog,
        )

        reg = MetricsRegistry()
        w = RecompileWatchdog(reg)
        rec = logging.LogRecord(
            "jax._src.interpreters.pxla", logging.WARNING, "f", 1,
            "Compiling fn with global shapes", (), None,
        )
        w.emit(rec)
        w.emit(
            logging.LogRecord(
                "jax._src.dispatch", logging.WARNING, "f", 1,
                "Finished tracing + transforming", (), None,
            )
        )
        assert w.count == 1
        assert reg.counter(RECOMPILES_TOTAL).value == 1

    def test_guard_dispatch_noop_when_inactive(self):
        from nm03_capstone_project_tpu.utils import sanitize

        assert not sanitize.active() or sanitize.state() is not None
        with sanitize.guard_transfers(False):
            pass  # must not import jax or raise

    def test_enable_trips_on_implicit_transfer(self):
        jax = pytest.importorskip("jax")
        import numpy as np

        from nm03_capstone_project_tpu.utils import sanitize

        f = jax.jit(lambda x: x + 1)
        x = jax.device_put(np.ones((4,), np.float32))
        f(x)
        with sanitize.guard_transfers(True):
            f(x)  # committed input: clean
            with pytest.raises(Exception):
                f(np.ones((4,), np.float32))  # implicit transfer: trips

    def test_driver_sanitize_flag_creates_counter(self, tmp_path):
        """--sanitize on a 2D driver: the snapshot must carry
        pipeline_recompiles_total (the acceptance's driver half)."""
        metrics = tmp_path / "m.json"
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "nm03_capstone_project_tpu.cli.sequential",
                "--device", "cpu",
                "--synthetic", "1",
                "--synthetic-slices", "2",
                "--canvas", "64",
                "--min-dim", "16",
                "--output", str(tmp_path / "out"),
                "--sanitize",
                "--metrics-out", str(metrics),
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=420,
        )
        assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
        snap = json.loads(metrics.read_text())
        names = {m["name"] for m in snap["metrics"]}
        assert "pipeline_recompiles_total" in names
        total = sum(
            m["value"]
            for m in snap["metrics"]
            if m["name"] == "pipeline_recompiles_total"
        )
        assert total >= 1  # the pipeline compiled at least once


class TestLockOrder:
    """NM42x (ISSUE 20): static lock-order analysis — the may-hold graph,
    cycle detection, blocking-under-a-lock, bare-acquire balance — plus
    the real-tree acceptance bar and the stripped-suppression break drill
    proving the tree is clean BECAUSE of the reasoned suppressions."""

    # -- NM421: lock-order cycles ---------------------------------------

    def test_nm421_abba_module_locks(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/pair.py": """
                import threading

                lock_a = threading.Lock()
                lock_b = threading.Lock()

                def forward():
                    with lock_a:
                        with lock_b:
                            pass

                def backward():
                    with lock_b:
                        with lock_a:
                            pass
                """
            },
            rules=(check_lock_order,),
        )
        assert "NM421" in rules_of(fs)

    def test_nm421_cycle_through_cross_class_calls(self, tmp_path):
        """The cycle the runtime can only hit under exact interleaving:
        A.outer holds A under B (via B.call_back), B.outer holds B under
        A — found statically by resolving annotated-attribute calls."""
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/xcls.py": """
                import threading

                class Alpha:
                    def __init__(self, beta: "Beta"):
                        self._lock = threading.Lock()
                        self.beta = beta

                    def outer(self):
                        with self._lock:
                            self.beta.inner()

                    def inner(self):
                        with self._lock:
                            pass

                class Beta:
                    def __init__(self, alpha: Alpha):
                        self._lock = threading.Lock()
                        self.alpha = alpha

                    def outer(self):
                        with self._lock:
                            self.alpha.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """
            },
            rules=(check_lock_order,),
        )
        assert "NM421" in rules_of(fs)

    def test_nm421_self_deadlock_nonreentrant(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/selfd.py": """
                import threading

                class W:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """
            },
            rules=(check_lock_order,),
        )
        assert "NM421" in rules_of(fs)

    def test_nm421_green_consistent_order_and_rlock(self, tmp_path):
        """Same pair always in the same order, and RLock re-entry: clean."""
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/clean.py": """
                import threading

                lock_a = threading.Lock()
                lock_b = threading.Lock()

                def one():
                    with lock_a:
                        with lock_b:
                            pass

                def two():
                    with lock_a:
                        with lock_b:
                            pass

                class R:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == []

    # -- NM422: blocking while holding a lock ---------------------------

    def test_nm422_sleep_and_urlopen_under_lock(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/blocky.py": """
                import threading
                import time
                from urllib.request import urlopen

                class W:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def slow(self):
                        with self._lock:
                            time.sleep(0.5)

                    def netty(self):
                        with self._lock:
                            urlopen("http://127.0.0.1:1/x")
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == ["NM422", "NM422"]

    def test_nm422_through_resolved_helper_call(self, tmp_path):
        """The blocking call hides one call-resolution hop away."""
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/hop.py": """
                import threading
                import time

                class W:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def _helper(self):
                        time.sleep(0.2)

                    def outer(self):
                        with self._lock:
                            self._helper()
                """
            },
            rules=(check_lock_order,),
        )
        assert "NM422" in rules_of(fs)

    def test_nm422_unbounded_result_join_wait(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/waits.py": """
                import threading

                class W:
                    def __init__(self, fut, thread, event):
                        self._lock = threading.Lock()
                        self.fut = fut
                        self.thread = thread
                        self.event = event

                    def bad(self):
                        with self._lock:
                            self.fut.result()

                    def ok(self):
                        with self._lock:
                            self.fut.result(timeout=1.0)
                        self.thread.join()
                        self.event.wait()
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == ["NM422"]

    def test_nm422_green_blocking_outside_lock(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/fine.py": """
                import threading
                import time

                class W:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def good(self):
                        with self._lock:
                            x = 1
                        time.sleep(0.5)
                        return x
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == []

    def test_nm422_suppression_with_reason_honored(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/sanc.py": """
                import threading
                import time

                class W:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def capture(self):
                        with self._lock:
                            # nm03-lint: disable=NM422 the sleep IS the capture window this lock serializes
                            time.sleep(0.5)
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == []

    def test_nm422_bare_suppression_degrades_to_nm390(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/serving/bare.py": """
                import threading
                import time

                class W:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def capture(self):
                        with self._lock:
                            time.sleep(0.5)  # nm03-lint: disable=NM422
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == ["NM390"]

    # -- NM423: bare acquire balance ------------------------------------

    def test_nm423_acquire_without_try_finally(self, tmp_path):
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/utils/bal.py": """
                import threading

                _lock = threading.Lock()

                def bad():
                    _lock.acquire()
                    do_thing()
                    _lock.release()
                """
            },
            rules=(check_lock_order,),
        )
        assert "NM423" in rules_of(fs)

    def test_nm423_green_try_finally(self, tmp_path):
        """The profiling.py pattern: acquire, then release in finally."""
        fs = lint_tree(
            tmp_path,
            {
                f"{PKG}/utils/balok.py": """
                import threading

                _lock = threading.Lock()

                def good():
                    if not _lock.acquire(blocking=False):
                        raise RuntimeError("busy")
                    try:
                        return do_thing()
                    finally:
                        _lock.release()
                """
            },
            rules=(check_lock_order,),
        )
        assert rules_of(fs) == []

    # -- the acceptance bar on the REAL tree ----------------------------

    def test_real_tree_lock_order_clean(self):
        """Zero NM42x findings (and zero NM390 from their suppressions) on
        the real tree: the 7 deliberate lock-holding dispatches all carry
        reasoned suppressions, there are no cycles, and every bare acquire
        balances in a try/finally."""
        parsed = collect_files(
            [REPO / PKG, REPO / "bench.py", REPO / "scripts"], REPO
        )
        fs = run_rules(parsed, (check_lock_order,), select=["NM42", "NM390"])
        assert rules_of(fs) == [], [f.render() for f in fs]

    def test_real_tree_graph_shape(self):
        """The graph the witness gate trusts: dozens of lock sites, the
        gang edges present, obs/ locks verified leaves."""
        parsed = collect_files(
            [REPO / PKG, REPO / "bench.py", REPO / "scripts"], REPO
        )
        graph = build_lock_graph(parsed)
        assert len(graph.nodes) >= 30
        assert graph.leaf_ok, graph.leaf_violations
        keys = {a for a, _ in graph.edges} | {b for _, b in graph.edges}
        gang = f"{PKG}/serving/batcher.py:DynamicBatcher._gang_lock"
        execu = f"{PKG}/serving/executor.py:WarmExecutor._lock"
        assert any(a == gang for a, _ in graph.edges), sorted(keys)
        # the property-access edge the runtime witness first exposed:
        # lane_count (a @property taking the executor lock) read while
        # holding the batcher stats lock
        batl = f"{PKG}/serving/batcher.py:DynamicBatcher._lock"
        assert (batl, execu) in graph.edges

    def test_break_drill_stripped_suppressions_trip_nm422(self, tmp_path):
        """Break drill: the package with every disable=NM422 suppression
        comment stripped must light up at the sanctioned hold sites —
        proving the rule sees them and the tree is clean because each one
        carries a reason, not because the rule is blind."""
        import shutil

        src_pkg = REPO / PKG
        dst_pkg = tmp_path / PKG
        shutil.copytree(
            src_pkg, dst_pkg,
            ignore=shutil.ignore_patterns("__pycache__", "*.pyc"),
        )
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        stripped = 0
        for py in dst_pkg.rglob("*.py"):
            text = py.read_text()
            if "disable=NM422" not in text:
                continue
            kept = [
                ln for ln in text.splitlines() if "disable=NM422" not in ln
            ]
            stripped += text.count("disable=NM422")
            py.write_text("\n".join(kept) + "\n")
        assert stripped >= 7, "expected the tree's sanctioned NM422 holds"
        parsed = collect_files([dst_pkg], tmp_path)
        fs = run_rules(parsed, (check_lock_order,), select=["NM42"])
        found = [f for f in fs if f.rule == "NM422"]
        assert len(found) >= stripped - 1, [f.render() for f in fs]
        hit_paths = {f.path for f in found}
        assert f"{PKG}/serving/batcher.py" in hit_paths
        assert f"{PKG}/serving/volumes.py" in hit_paths

    # -- the witness gate (explain_witness unit face) -------------------

    def _graph(self):
        parsed = collect_files(
            [REPO / PKG, REPO / "bench.py", REPO / "scripts"], REPO
        )
        return build_lock_graph(parsed)

    def test_explain_witness_accepts_static_edge(self):
        graph = self._graph()
        gangl = f"{PKG}/serving/batcher.py:DynamicBatcher._gang_lock"
        execl = f"{PKG}/serving/executor.py:WarmExecutor._lock"
        sites = {n.key: (n.path, n.line) for n in graph.nodes.values()}
        gp, gl = sites[gangl]
        ep, el = sites[execl]
        witness = {
            "version": 1,
            "sites": [
                {"id": f"{gp}:{gl}", "path": gp, "line": gl, "kind": "Lock"},
                {"id": f"{ep}:{el}", "path": ep, "line": el, "kind": "Lock"},
            ],
            "edges": [
                {"src": f"{gp}:{gl}", "dst": f"{ep}:{el}", "count": 3}
            ],
            "inversions": [],
            "over_budget": [],
        }
        assert explain_witness(witness, graph) == []

    def test_explain_witness_flags_inversion_and_unexplained(self):
        graph = self._graph()
        sites = {n.key: (n.path, n.line) for n in graph.nodes.values()}
        gp, gl = sites[f"{PKG}/serving/batcher.py:DynamicBatcher._gang_lock"]
        rp, rl = sites[f"{PKG}/ingest/ring.py:StagingRing._lock"]
        witness = {
            "version": 1,
            "sites": [
                {"id": f"{gp}:{gl}", "path": gp, "line": gl, "kind": "Lock"},
                {"id": f"{rp}:{rl}", "path": rp, "line": rl, "kind": "Lock"},
            ],
            # ring -> gang is in NO static path: unexplained
            "edges": [
                {"src": f"{rp}:{rl}", "dst": f"{gp}:{gl}", "count": 1}
            ],
            "inversions": [
                {"first": f"{rp}:{rl}", "second": f"{gp}:{gl}",
                 "stack": ["x.py:1 in a"], "prior_stack": ["y.py:2 in b"]}
            ],
            "over_budget": [],
        }
        problems = explain_witness(witness, graph)
        assert any("inversion" in p for p in problems)
        assert any("not explained" in p for p in problems)

    def test_explain_witness_flags_unregistered_package_site(self):
        graph = self._graph()
        witness = {
            "version": 1,
            "sites": [
                {"id": f"{PKG}/serving/batcher.py:9999",
                 "path": f"{PKG}/serving/batcher.py", "line": 9999,
                 "kind": "Lock"},
            ],
            "edges": [], "inversions": [], "over_budget": [],
        }
        problems = explain_witness(witness, graph)
        assert any("not in the static lock registry" in p for p in problems)


class TestJsonStableOrder:
    def test_findings_sorted_by_path_line_rule(self, tmp_path):
        """--format json emits findings in (path, line, rule) order — the
        diffable contract CI consumers rely on."""
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        mod = tmp_path / PKG / "serving"
        mod.mkdir(parents=True)
        (mod / "zz.py").write_text(
            "import threading\nimport time\n_l = threading.Lock()\n"
            "def f():\n    with _l:\n        time.sleep(1)\n"
            "        time.sleep(2)\n"
        )
        (mod / "aa.py").write_text(
            "import threading\nimport time\n_l = threading.Lock()\n"
            "def f():\n    with _l:\n        time.sleep(1)\n"
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.analysis.cli",
                "--root", str(tmp_path), "--no-baseline", "--format", "json",
                str(tmp_path / PKG),
            ],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        payload = json.loads(proc.stdout)
        got = [
            (f["path"], f["line"], f["rule"]) for f in payload["findings"]
        ]
        assert got == sorted(got)
        assert len(got) >= 3  # both files, both sleeps in zz.py


class TestPruneBaseline:
    def _fixture(self, tmp_path, violating: bool):
        (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
        mod = tmp_path / PKG / "resilience"
        mod.mkdir(parents=True, exist_ok=True)
        (mod / "policy.py").write_text(
            "import jax\n" if violating else "x = 1\n"
        )

    def _cli(self, tmp_path, *extra):
        return subprocess.run(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.analysis.cli",
                "--root", str(tmp_path),
                "--baseline", str(tmp_path / "bl.json"),
                str(tmp_path / PKG), *extra,
            ],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )

    def test_prune_drops_stale_entries(self, tmp_path):
        self._fixture(tmp_path, violating=True)
        assert self._cli(tmp_path, "--update-baseline").returncode == 0
        bl = json.loads((tmp_path / "bl.json").read_text())
        assert len(bl["entries"]) >= 1
        assert any(e["rule"] == "NM301" for e in bl["entries"])
        # fix the finding, then prune: the stale NM301 leaves the baseline
        # (the fixture's NM302 registry findings stay live, so they stay)
        self._fixture(tmp_path, violating=False)
        proc = self._cli(tmp_path, "--prune-baseline")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "dropped" in proc.stdout and "0 stale" not in proc.stdout
        bl2 = json.loads((tmp_path / "bl.json").read_text())
        assert len(bl2["entries"]) < len(bl["entries"])
        assert not any(e["rule"] == "NM301" for e in bl2["entries"])

    def test_prune_keeps_live_entries(self, tmp_path):
        self._fixture(tmp_path, violating=True)
        assert self._cli(tmp_path, "--update-baseline").returncode == 0
        before = json.loads((tmp_path / "bl.json").read_text())
        proc = self._cli(tmp_path, "--prune-baseline")
        assert proc.returncode == 0
        after = json.loads((tmp_path / "bl.json").read_text())
        assert after == before  # nothing stale, nothing dropped

    def test_prune_refuses_narrowed_run(self):
        """--select narrows the findings to a slice; pruning against the
        slice would drop every entry outside it. Exit 2, like
        --update-baseline's refusal."""
        proc = subprocess.run(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.analysis.cli",
                "--root", str(REPO), "--select", "NM301",
                "--prune-baseline",
            ],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert proc.returncode == 2
        assert "refusing --prune-baseline" in proc.stderr

    def test_real_tree_prune_is_a_noop(self, tmp_path):
        """The checked-in baseline is fully live: pruning a COPY drops 0."""
        import shutil

        bl = tmp_path / "bl.json"
        shutil.copyfile(REPO / "nm03lint_baseline.json", bl)
        proc = subprocess.run(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.analysis.cli",
                "--root", str(REPO), "--baseline", str(bl),
                "--prune-baseline",
            ],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 stale" in proc.stdout
        assert json.loads(bl.read_text()) == json.loads(
            (REPO / "nm03lint_baseline.json").read_text()
        )
