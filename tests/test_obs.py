"""Observability subsystem tests.

Covers the ISSUE-1 contract: registry semantics (counter monotonicity,
histogram bucketing, Prometheus exposition), span nesting + device-sync
behavior, JSONL event schema round-trip (run id + git SHA on every record,
exactly one terminal outcome per patient), the drivers' ``--metrics-out`` /
``--log-json`` wiring on synthetic data, and the scripts/check_telemetry.py
schema gate (OK on real artifacts, non-zero on drift).
"""

import io
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from nm03_capstone_project_tpu import obs
from nm03_capstone_project_tpu.obs import (
    EventLog,
    Heartbeat,
    MetricsRegistry,
    RunContext,
    SpanRecorder,
)

REPO = Path(__file__).resolve().parents[1]
CHECKER = REPO / "scripts" / "check_telemetry.py"


def run_checker(*argv):
    return subprocess.run(
        [sys.executable, str(CHECKER), *map(str, argv)],
        capture_output=True, text=True, timeout=60,
    )


# -- metrics registry ------------------------------------------------------


class TestRegistry:
    def test_counter_monotone(self):
        r = MetricsRegistry()
        c = r.counter("nm03_things_total", status="ok")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)
        assert c.value == 3.5

    def test_get_or_create_identity_and_label_isolation(self):
        r = MetricsRegistry()
        a = r.counter("nm03_x_total", status="ok")
        b = r.counter("nm03_x_total", status="ok")
        other = r.counter("nm03_x_total", status="failed")
        assert a is b and a is not other
        a.inc()
        assert other.value == 0

    def test_kind_conflict_rejected(self):
        r = MetricsRegistry()
        r.counter("nm03_x_total")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("nm03_x_total")

    def test_name_and_label_hygiene(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError, match="invalid metric name"):
            r.counter("bad-name")
        with pytest.raises(ValueError, match="invalid label name"):
            r.counter("nm03_ok_total", **{"bad-label": "x"})

    def test_histogram_bucketing(self):
        r = MetricsRegistry()
        h = r.histogram("nm03_lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        cum = h.cumulative()
        assert [le for le, _ in cum] == ["0.1", "1", "10", "+Inf"]
        assert [n for _, n in cum] == [1, 3, 4, 5]  # cumulative
        assert h.count == 5
        assert h.sum == pytest.approx(56.05)
        with pytest.raises(ValueError, match="strictly increasing"):
            r.histogram("nm03_bad_seconds", buckets=(1.0, 1.0))

    def test_snapshot_schema(self):
        r = MetricsRegistry()
        r.counter("nm03_c_total", help="c").inc(3)
        r.gauge("nm03_g").set(-1.5)
        r.histogram("nm03_h_seconds", buckets=(1.0,)).observe(0.5)
        snap = r.snapshot(run_id="rid", git_sha="sha")
        assert snap["schema"] == "nm03.metrics.v1"
        assert snap["run_id"] == "rid" and snap["git_sha"] == "sha"
        by_name = {m["name"]: m for m in snap["metrics"]}
        assert by_name["nm03_c_total"]["value"] == 3
        assert by_name["nm03_g"]["value"] == -1.5
        hist = by_name["nm03_h_seconds"]
        assert hist["buckets"][-1] == ["+Inf", 1] and hist["count"] == 1
        json.dumps(snap)  # JSON-able end to end

    def test_prometheus_exposition(self):
        r = MetricsRegistry()
        r.counter("nm03_c_total", help="things", status="ok").inc(2)
        r.histogram("nm03_h_seconds", buckets=(0.5,), stage="decode").observe(0.1)
        text = r.to_prometheus()
        assert "# TYPE nm03_c_total counter" in text
        assert 'nm03_c_total{status="ok"} 2' in text
        assert "# TYPE nm03_h_seconds histogram" in text
        assert 'nm03_h_seconds_bucket{stage="decode",le="0.5"} 1' in text
        assert 'nm03_h_seconds_bucket{stage="decode",le="+Inf"} 1' in text
        assert 'nm03_h_seconds_count{stage="decode"} 1' in text

    def test_thread_safety_under_contention(self):
        import concurrent.futures as cf

        r = MetricsRegistry()
        c = r.counter("nm03_n_total")

        def spin(_):
            for _ in range(1000):
                c.inc()

        with cf.ThreadPoolExecutor(8) as pool:
            list(pool.map(spin, range(8)))
        assert c.value == 8000


# -- spans -----------------------------------------------------------------


class TestSpans:
    def test_nesting_and_report(self):
        s = SpanRecorder()
        with s.span("outer"):
            assert s.depth == 1 and s.current_path() == "outer"
            with s.span("inner"):
                assert s.depth == 2 and s.current_path() == "outer/inner"
        assert s.depth == 0
        with s.span("outer"):  # re-entrant accumulation
            pass
        assert s.counts == {"outer": 2, "inner": 1}
        assert set(s.report()) == {"outer", "inner"}
        assert s.report()["outer"] >= s.report()["inner"]

    def test_histogram_feeding_with_bounded_stage_label(self):
        r = MetricsRegistry()
        s = SpanRecorder(registry=r)
        for pid in ("P1", "P2", "P3"):
            with s.span(f"load/{pid}"):
                pass
        with s.span("compute"):
            pass
        # per-patient section names collapse onto one stage label
        h = r.get("nm03_stage_latency_seconds", stage="load")
        assert h is not None and h.count == 3
        assert r.get("nm03_stage_latency_seconds", stage="compute").count == 1
        # report() keeps the per-patient keys (Timer contract)
        assert "load/P1" in s.report()

    def test_sync_called_on_tree(self, monkeypatch):
        import nm03_capstone_project_tpu.utils.timing as timing

        synced = []
        monkeypatch.setattr(timing, "sync", lambda tree: synced.append(tree))
        s = SpanRecorder()
        with s.span("compute", tree={"a": 1}):
            pass
        assert synced == [{"a": 1}]

    def test_timer_alias_is_span_recorder(self):
        from nm03_capstone_project_tpu.utils.timing import Timer

        t = Timer()
        assert isinstance(t, SpanRecorder)
        with t.section("x"):
            pass
        assert t.report()["x"] >= 0


# -- event log -------------------------------------------------------------


class TestEventLog:
    def test_schema_round_trip(self, tmp_path):
        path = tmp_path / "e.jsonl"
        log = EventLog(path=path, run_id="rid", git_sha="sha")
        log.emit("run_started", driver="test")
        log.emit("thing", level="WARNING", detail={"k": 1})
        log.emit("run_finished", status="ok")
        log.close()
        records = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(records) == 3
        for i, rec in enumerate(records):
            assert rec["schema"] == "nm03.events.v1"
            assert rec["run_id"] == "rid" and rec["git_sha"] == "sha"
            assert rec["seq"] == i
            assert isinstance(rec["ts_unix"], float)
            assert isinstance(rec["mono_s"], float)
        assert records[1]["level"] == "WARNING"
        assert records[1]["detail"] == {"k": 1}
        assert [r["seq"] for r in records] == sorted(r["seq"] for r in records)

    def test_envelope_protected(self):
        log = EventLog(stream=io.StringIO(), run_id="r", git_sha="s")
        with pytest.raises(ValueError, match="shadow the run envelope"):
            log.emit("x", seq=99)
        with pytest.raises(ValueError, match="unknown level"):
            log.emit("x", level="LOUD")

    def test_sinkless_log_keeps_tail(self):
        log = EventLog(run_id="r", git_sha="s")
        assert not log.enabled
        rec = log.emit("x", a=1)
        assert rec["a"] == 1 and list(log.tail) == [rec]

    def test_one_run_per_file_truncates(self, tmp_path):
        # two runs into one path must leave ONE valid stream (the schema
        # demands a single run_id; appending would fail the validator)
        path = tmp_path / "e.jsonl"
        for run_id in ("run-a", "run-b"):
            log = EventLog(path=path, run_id=run_id, git_sha="s")
            log.emit("run_started")
            log.emit("run_finished")
            log.close()
        records = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(records) == 2
        assert {r["run_id"] for r in records} == {"run-b"}

    def test_sink_write_failure_degrades_not_raises(self, capsys):
        class ExplodingStream(io.StringIO):
            def write(self, s):
                raise OSError("disk full")

        log = EventLog(stream=ExplodingStream(), run_id="r", git_sha="s")
        rec = log.emit("x")  # must not raise: telemetry never costs the run
        assert rec["event"] == "x"
        assert not log.enabled  # sink disabled after the failure
        log.emit("y")  # subsequent emits keep working sink-less
        assert [r["event"] for r in log.tail] == ["x", "y"]
        assert "telemetry sink disabled" in capsys.readouterr().err

    def test_heartbeat_emits_counter_totals(self):
        stream = io.StringIO()
        log = EventLog(stream=stream, run_id="r", git_sha="s")
        reg = MetricsRegistry()
        reg.counter("nm03_done_total").inc(7)
        hb = Heartbeat(log, interval_s=0.05, registry=reg).start()
        time.sleep(0.2)
        hb.stop()
        beats = [
            json.loads(l) for l in stream.getvalue().splitlines()
            if json.loads(l)["event"] == "heartbeat"
        ]
        assert beats and beats[0]["counters"] == {"nm03_done_total": 7}
        assert beats[0]["uptime_s"] > 0


# -- run context -----------------------------------------------------------


class TestRunContext:
    def test_patient_outcome_exactly_once(self):
        ctx = RunContext.create("test", stream=io.StringIO())
        ctx.patient_outcome("P1", "ok", slices_total=4, slices_ok=4)
        with pytest.raises(RuntimeError, match="duplicate patient_outcome"):
            ctx.patient_outcome("P1", "failed")
        assert ctx.has_outcome("P1") and not ctx.has_outcome("P2")
        with pytest.raises(ValueError, match="not in"):
            ctx.patient_outcome("P2", "exploded")
        counter = ctx.registry.get(obs.PATIENT_OUTCOMES_TOTAL, status="ok")
        assert counter.value == 1

    def test_failed_and_truncated_outcomes_are_warnings(self):
        stream = io.StringIO()
        ctx = RunContext.create("test", stream=stream)
        ctx.patient_outcome("P1", "failed", error_class="ValueError")
        ctx.patient_outcome("P2", "ok", slices_total=3, slices_ok=3,
                            slices_truncated=2)
        ctx.grow_truncated("P2", count=2)
        ctx.close()
        by_event = {}
        for line in stream.getvalue().splitlines():
            rec = json.loads(line)
            by_event.setdefault(rec["event"], []).append(rec)
        assert [r["level"] for r in by_event["patient_outcome"]] == [
            "WARNING", "WARNING"  # failed; truncated
        ]
        assert by_event["grow_truncated"][0]["level"] == "WARNING"
        assert by_event["run_finished"][0] == json.loads(
            stream.getvalue().splitlines()[-1]
        )
        assert ctx.registry.get(obs.GROW_TRUNCATED_TOTAL).value == 2

    def test_close_idempotent_and_writes_metrics(self, tmp_path):
        m = tmp_path / "m.json"
        ctx = RunContext.create("test", metrics_out=m)
        ctx.registry.counter("nm03_x_total").inc()
        ctx.close()
        ctx.close()  # second close is a no-op
        snap = json.loads(m.read_text())
        assert snap["schema"] == "nm03.metrics.v1"
        assert snap["run_id"] == ctx.events.run_id

    def test_log_bridge_mirrors_warnings(self, tmp_path):
        from nm03_capstone_project_tpu.utils.reporter import get_logger

        path = tmp_path / "e.jsonl"
        ctx = RunContext.create("test", log_json=path)
        get_logger("runner").warning("failed to read %s: %s", "f.dcm", "boom")
        ctx.close()
        logs = [
            json.loads(l) for l in path.read_text().splitlines()
            if json.loads(l)["event"] == "log"
        ]
        assert logs and logs[0]["level"] == "WARNING"
        assert "f.dcm" in logs[0]["message"]


# -- cohort-runner telemetry on synthetic data ----------------------------


@pytest.fixture(scope="module")
def cohort(tmp_path_factory):
    from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

    root = tmp_path_factory.mktemp("obs-cohort")
    write_synthetic_cohort(root, n_patients=2, n_slices=3, height=128, width=128)
    return root


class TestRunnerTelemetry:
    def test_truncation_surfaced_as_event_and_counter(self, cohort, tmp_path):
        from nm03_capstone_project_tpu.cli.runner import CohortProcessor
        from nm03_capstone_project_tpu.config import PipelineConfig

        capped = PipelineConfig(
            canvas=128, render_size=128, grow_block_iters=1, grow_max_iters=2
        )
        ctx = RunContext.create("parallel", stream=io.StringIO())
        proc = CohortProcessor(
            cohort, tmp_path / "o", cfg=capped, mode="parallel", obs=ctx
        )
        summary = proc.process_all_patients()
        assert summary.as_dict()["slices_truncated"] > 0
        assert (
            ctx.registry.get(obs.GROW_TRUNCATED_TOTAL).value
            == summary.as_dict()["slices_truncated"]
        )
        trunc_events = [
            r for r in ctx.events.tail if r["event"] == "grow_truncated"
        ]
        assert trunc_events and all(r["level"] == "WARNING" for r in trunc_events)
        outcomes = [r for r in ctx.events.tail if r["event"] == "patient_outcome"]
        assert len(outcomes) == 2
        assert all(r["grow_truncated"] for r in outcomes)

    def test_failed_patient_gets_failed_outcome(self, tmp_path):
        from nm03_capstone_project_tpu.cli.runner import CohortProcessor
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        root = tmp_path / "c"
        write_synthetic_cohort(root, 1, n_slices=2, height=128, width=128)
        (root / "PGBM-0002").mkdir()  # patient with no series -> load failure
        ctx = RunContext.create("sequential", stream=io.StringIO())
        proc = CohortProcessor(
            root, tmp_path / "o",
            cfg=PipelineConfig(canvas=128, render_size=128),
            mode="sequential", obs=ctx,
        )
        proc.process_all_patients()
        outcomes = {
            r["patient_id"]: r
            for r in ctx.events.tail
            if r["event"] == "patient_outcome"
        }
        assert outcomes["PGBM-0001"]["status"] == "ok"
        assert outcomes["PGBM-0002"]["status"] == "failed"
        assert outcomes["PGBM-0002"]["error_class"]
        assert ctx.registry.get(
            obs.PATIENT_OUTCOMES_TOTAL, status="failed"
        ).value == 1


# -- CLI smoke + validator -------------------------------------------------


class TestCliTelemetry:
    def test_sequential_artifacts_validate(self, tmp_path):
        from nm03_capstone_project_tpu.cli import sequential

        m, e = tmp_path / "m.json", tmp_path / "e.jsonl"
        rc = sequential.main(
            [
                "--synthetic", "2", "--synthetic-slices", "2",
                "--canvas", "128", "--render-size", "128",
                "--device", "cpu",
                "--output", str(tmp_path / "out"),
                "--metrics-out", str(m),
                "--log-json", str(e),
                "--results-json", str(tmp_path / "r.json"),
            ]
        )
        assert rc == 0

        # every record carries the run envelope; one terminal outcome/patient
        records = [json.loads(l) for l in e.read_text().splitlines()]
        assert all(r["run_id"] == records[0]["run_id"] for r in records)
        assert all(r["git_sha"] == records[0]["git_sha"] for r in records)
        assert records[0]["event"] == "run_started"
        assert records[-1]["event"] == "run_finished"
        outcomes = [r for r in records if r["event"] == "patient_outcome"]
        assert sorted(r["patient_id"] for r in outcomes) == [
            "PGBM-0001", "PGBM-0002"
        ]

        # metrics: per-stage latency histograms + per-patient outcome counters
        snap = json.loads(m.read_text())
        by = {(x["name"], tuple(sorted(x["labels"].items()))): x
              for x in snap["metrics"]}
        stages = {k[1][0][1] for k in by if k[0] == "nm03_stage_latency_seconds"}
        assert {"decode", "compute", "export"} <= stages
        ok = by[("nm03_patient_outcomes_total", (("status", "ok"),))]
        assert ok["value"] == 2
        assert snap["run_id"] == records[0]["run_id"]

        # results JSON embeds the same snapshot
        results = json.loads((tmp_path / "r.json").read_text())
        assert results["metrics"]["schema"] == "nm03.metrics.v1"

        # the documented gate passes on real artifacts
        out = run_checker(
            "--events", e, "--metrics", m, "--expect-patients", "2"
        )
        assert out.returncode == 0, out.stderr

    def test_volume_artifacts_validate(self, tmp_path):
        from nm03_capstone_project_tpu.cli import volume

        m, e = tmp_path / "m.json", tmp_path / "e.jsonl"
        rc = volume.main(
            [
                "--synthetic", "1", "--synthetic-slices", "3",
                "--canvas", "128", "--render-size", "128",
                "--device", "cpu",
                "--output", str(tmp_path / "out"),
                "--metrics-out", str(m),
                "--log-json", str(e),
            ]
        )
        assert rc == 0
        out = run_checker("--events", e, "--metrics", m, "--expect-patients", "1")
        assert out.returncode == 0, out.stderr
        snap = json.loads(m.read_text())
        names = {x["name"] for x in snap["metrics"]}
        assert "nm03_patient_outcomes_total" in names
        assert "nm03_stage_latency_seconds" in names

    def test_checker_rejects_drift(self, tmp_path):
        # missing envelope key
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"schema": "nm03.events.v1", "event": "x"}\n')
        assert run_checker("--events", bad).returncode == 1

        # duplicate terminal outcome for one patient
        log = EventLog(path=tmp_path / "dup.jsonl", run_id="r", git_sha="s")
        log.emit("run_started")
        for _ in range(2):
            log.emit("patient_outcome", patient_id="P1", status="ok",
                     slices_total=1, slices_ok=1, slices_failed=0,
                     slices_truncated=0, grow_truncated=False,
                     error_class=None, retries=0)
        log.emit("run_finished")
        log.close()
        out = run_checker("--events", tmp_path / "dup.jsonl")
        assert out.returncode == 1 and "terminal outcomes" in out.stderr

        # histogram whose buckets are not cumulative
        snap = {
            "schema": "nm03.metrics.v1", "run_id": "r", "git_sha": "s",
            "created_unix": 1.0,
            "metrics": [{
                "name": "nm03_h_seconds", "type": "histogram", "labels": {},
                "buckets": [["1", 5], ["+Inf", 3]], "sum": 1.0, "count": 3,
            }],
        }
        (tmp_path / "bad_m.json").write_text(json.dumps(snap))
        out = run_checker("--metrics", tmp_path / "bad_m.json")
        assert out.returncode == 1 and "cumulative" in out.stderr

        # run_id mismatch across the two artifacts
        good_snap = dict(snap, metrics=[], run_id="OTHER")
        (tmp_path / "m2.json").write_text(json.dumps(good_snap))
        log2 = EventLog(path=tmp_path / "e2.jsonl", run_id="r", git_sha="s")
        log2.emit("run_started")
        log2.emit("run_finished")
        log2.close()
        out = run_checker(
            "--events", tmp_path / "e2.jsonl", "--metrics", tmp_path / "m2.json"
        )
        assert out.returncode == 1 and "run_id" in out.stderr
