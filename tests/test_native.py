"""Native C++ runtime layer vs its Python reference implementations.

The contract: the C++ DICOM parser (csrc/nm03native.cpp) decodes exactly what
data.dicomlite decodes; the threaded batch loader reproduces the runner's
decode/pad/guard semantics; the JPEG encoder produces baseline JPEGs that
PIL decodes back to within a small PSNR of the input.
"""

import numpy as np
import pytest

from nm03_capstone_project_tpu.data.dicomlite import read_dicom, write_dicom
from nm03_capstone_project_tpu.data.synthetic import phantom_slice
from nm03_capstone_project_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native layer unavailable (no g++?)"
)


def _write_slice(path, h=64, w=48, seed=0, slope=2.0, intercept=-100.0):
    rng = np.random.default_rng(seed)
    pixels = rng.integers(0, 4000, size=(h, w)).astype(np.uint16)
    write_dicom(
        path, pixels, rescale_slope=slope, rescale_intercept=intercept
    )
    return pixels


class TestNativeDicom:
    def test_matches_python_reader(self, tmp_path):
        p = tmp_path / "a.dcm"
        _write_slice(p, h=70, w=50, seed=1)
        py = read_dicom(p)
        nat = native.read_dicom_native(p)
        assert nat.shape == (70, 50)
        assert nat.dtype == np.float32
        np.testing.assert_array_equal(nat, py.pixels)

    def test_rescale_applied(self, tmp_path):
        p = tmp_path / "r.dcm"
        raw = _write_slice(p, h=16, w=16, seed=2, slope=0.5, intercept=10.0)
        nat = native.read_dicom_native(p)
        np.testing.assert_allclose(
            nat, raw.astype(np.float32) * 0.5 + 10.0, rtol=1e-6
        )

    @pytest.mark.parametrize("ts_name", ["RLE_LOSSLESS", "JPEG_LOSSLESS_SV1"])
    def test_compressed_matches_python_reader(self, tmp_path, ts_name):
        """The C++ parser decodes RLE and JPEG Lossless natively,
        bit-identical to the Python reader's codecs.py path."""
        from nm03_capstone_project_tpu.data import dicomlite

        rng = np.random.default_rng(7)
        img = rng.integers(0, 4000, size=(70, 50)).astype(np.uint16)
        img[:20, :20] = 99  # replicate runs
        p = tmp_path / "c.dcm"
        write_dicom(p, img, rescale_slope=2.0, rescale_intercept=-10.0,
                    transfer_syntax=getattr(dicomlite, ts_name))
        nat = native.read_dicom_native(p)
        py = read_dicom(p)
        np.testing.assert_array_equal(nat, py.pixels)

    @staticmethod
    def _encapsulated_dicom(path, fragments, rows, cols, bits=16):
        """Hand-build a JPEG-lossless Part-10 file from raw fragments."""
        import struct

        from nm03_capstone_project_tpu.data.dicomlite import (
            _element,
            _ITEM,
            _SEQ_DELIM,
            JPEG_LOSSLESS,
        )

        items = struct.pack("<HHI", *_ITEM, 0)  # empty Basic Offset Table
        for frag in fragments:
            if len(frag) % 2:
                frag += b"\x00"
            items += struct.pack("<HHI", *_ITEM, len(frag)) + frag
        items += struct.pack("<HHI", *_SEQ_DELIM, 0)
        meta_elems = _element(0x0002, 0x0010, b"UI", JPEG_LOSSLESS.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", struct.pack("<I", len(meta_elems)))
            + meta_elems
        )
        ds = (
            _element(0x0028, 0x0002, b"US", struct.pack("<H", 1))
            + _element(0x0028, 0x0010, b"US", struct.pack("<H", rows))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", cols))
            + _element(0x0028, 0x0100, b"US", struct.pack("<H", bits))
            + _element(0x0028, 0x0103, b"US", struct.pack("<H", 0))
            + struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
            + items
        )
        path.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)

    @pytest.mark.parametrize("sel", [2, 3, 4, 5, 6, 7])
    def test_jpegll_predictors_native_matches_python(self, tmp_path, sel):
        """Predictor selections 2-7: both decoders apply the same (well-
        defined) prediction to the same entropy stream, so outputs must be
        bit-identical even though the stream was entropy-coded for SV1."""
        from nm03_capstone_project_tpu.data import codecs

        rng = np.random.default_rng(sel)
        img = rng.integers(0, 4096, (23, 31)).astype(np.uint16)
        stream = bytearray(codecs.jpeg_lossless_encode(img))
        sos = stream.index(b"\xff\xda")
        assert stream[sos + 4 + 3] == 1  # Ss byte (SV1 as written)
        stream[sos + 4 + 3] = sel
        py = codecs.jpeg_lossless_decode(bytes(stream))
        p = tmp_path / "sel.dcm"
        self._encapsulated_dicom(p, [bytes(stream)], 23, 31)
        nat = native.read_dicom_native(p)
        np.testing.assert_array_equal(nat, py.astype(np.float32))

    def test_jpegll_point_transform_native_matches_python(self, tmp_path):
        from nm03_capstone_project_tpu.data import codecs

        rng = np.random.default_rng(42)
        img = rng.integers(0, 4096, (16, 20)).astype(np.uint16)
        stream = bytearray(codecs.jpeg_lossless_encode(img))
        sos = stream.index(b"\xff\xda")
        stream[sos + 4 + 5] = 2  # Al = point transform 2
        py = codecs.jpeg_lossless_decode(bytes(stream))
        p = tmp_path / "pt.dcm"
        self._encapsulated_dicom(p, [bytes(stream)], 16, 20)
        np.testing.assert_array_equal(
            native.read_dicom_native(p), py.astype(np.float32)
        )

    def test_jpegll_8bit_native_matches_python(self, tmp_path):
        from nm03_capstone_project_tpu.data import codecs

        rng = np.random.default_rng(3)
        img = rng.integers(0, 256, (17, 19)).astype(np.uint16)
        stream = codecs.jpeg_lossless_encode(img, precision=8)
        py = codecs.jpeg_lossless_decode(stream)
        p = tmp_path / "p8.dcm"
        self._encapsulated_dicom(p, [stream], 17, 19, bits=8)
        np.testing.assert_array_equal(
            native.read_dicom_native(p), py.astype(np.float32)
        )

    def test_jpegll_multifragment_native_matches_python(self, tmp_path):
        from nm03_capstone_project_tpu.data import codecs

        rng = np.random.default_rng(9)
        img = rng.integers(0, 65536, (32, 32)).astype(np.uint16)
        stream = codecs.jpeg_lossless_encode(img)
        cut = len(stream) // 2
        if cut % 2:  # fragments must be even-length without padding bytes
            cut += 1  # landing mid-stream; both halves rejoin exactly
        p = tmp_path / "mf.dcm"
        self._encapsulated_dicom(p, [stream[:cut], stream[cut:]], 32, 32)
        np.testing.assert_array_equal(
            native.read_dicom_native(p), img.astype(np.float32)
        )

    def test_jpegll_malformed_segments_fail_cleanly(self, tmp_path):
        """Hostile streams must return a parse error, never crash: zero-length
        marker segment (the size_t underflow), bad precision, bad SSSS."""
        from nm03_capstone_project_tpu.data import codecs

        img = np.arange(64, dtype=np.uint16).reshape(8, 8)
        stream = bytearray(codecs.jpeg_lossless_encode(img))
        # (a) DHT segment claiming length 0
        dht = stream.index(b"\xff\xc4")
        bad = bytes(stream[:dht + 2]) + b"\x00\x00" + bytes(stream[dht + 2:])
        p = tmp_path / "m1.dcm"
        self._encapsulated_dicom(p, [bad], 8, 8)
        with pytest.raises(ValueError):
            native.read_dicom_native(p)
        # (b) SOF3 precision 0
        sof = stream.index(b"\xff\xc3")
        bad2 = bytearray(stream)
        bad2[sof + 4] = 0
        p2 = tmp_path / "m2.dcm"
        self._encapsulated_dicom(p2, [bytes(bad2)], 8, 8)
        with pytest.raises(ValueError):
            native.read_dicom_native(p2)

    def test_trailing_fill_bytes_rejected_cleanly(self, tmp_path):
        """A fragment ending in 0xFF fill bytes used to read one byte past
        the buffer after the fill-skip loop, making acceptance depend on
        out-of-bounds memory (ADVICE r4) — must be a clean parse error."""
        for i, frag in enumerate(
            [b"\xff\xd8\xff\xff", b"\xff\xd8\xff\xff\xff\xff\xff\xff"]
        ):
            p = tmp_path / f"fill{i}.dcm"
            self._encapsulated_dicom(p, [frag], 8, 8)
            with pytest.raises(ValueError):
                native.read_dicom_native(p)

    def test_mutation_fuzz_never_crashes(self, tmp_path):
        """Byte-corrupted DICOMs (plain, RLE, JPEG-lossless) must decode or
        raise — never kill the process. Exercises the C-ABI exception
        barriers and every header-validation path with seeded corruption."""
        from nm03_capstone_project_tpu.data.dicomlite import (
            DicomParseError,
            JPEG_LOSSLESS_SV1,
            RLE_LOSSLESS,
        )

        rng = np.random.default_rng(123)
        img = rng.integers(0, 4000, size=(24, 28)).astype(np.uint16)
        sources = []
        for i, ts in enumerate([None, RLE_LOSSLESS, JPEG_LOSSLESS_SV1]):
            p = tmp_path / f"src{i}.dcm"
            kw = {"transfer_syntax": ts} if ts else {}
            write_dicom(p, img, **kw)
            sources.append(p.read_bytes())
        p = tmp_path / "mut.dcm"
        for trial in range(120):
            raw = bytearray(sources[trial % len(sources)])
            for _ in range(rng.integers(1, 6)):
                mode = rng.integers(0, 3)
                if mode == 0:  # flip bytes
                    raw[rng.integers(0, len(raw))] = rng.integers(0, 256)
                elif mode == 1 and len(raw) > 140:  # truncate
                    raw = raw[: rng.integers(132, len(raw))]
                else:  # splice garbage
                    at = rng.integers(0, len(raw))
                    raw[at:at] = bytes(rng.integers(0, 256, 8, dtype=np.uint8))
            p.write_bytes(bytes(raw))
            try:
                out = native.read_dicom_native(p)
                assert out.ndim == 2  # decoded despite corruption: fine
            except ValueError:
                pass  # clean rejection: fine
            # the Python reader must hold the same contract
            try:
                read_dicom(p)
            except DicomParseError:
                pass

    def test_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.dcm"
        p.write_bytes(b"not a dicom file at all, definitely not")
        with pytest.raises(ValueError):
            native.read_dicom_native(p)

    def test_rejects_truncated(self, tmp_path):
        p = tmp_path / "t.dcm"
        _write_slice(p)
        data = p.read_bytes()
        p.write_bytes(data[: len(data) // 2])
        with pytest.raises(ValueError):
            native.read_dicom_native(p)

    def test_overlong_pixeldata_length_clamped_like_python(self, tmp_path):
        """A PixelData length that overruns the file must not be fatal if
        rows*cols bytes remain (Python slice-clamp semantics)."""
        import struct

        p = tmp_path / "o.dcm"
        _write_slice(p, h=8, w=8)
        data = bytearray(p.read_bytes())
        # PixelData element: tag (7FE0,0010) VR OW, 2 reserved, 4-byte length
        i = data.find(bytes.fromhex("e07f1000") + b"OW")
        assert i > 0
        (orig_len,) = struct.unpack_from("<I", data, i + 8)
        struct.pack_into("<I", data, i + 8, orig_len + 1000)
        p.write_bytes(bytes(data))
        py = read_dicom(p)
        nat = native.read_dicom_native(p)
        np.testing.assert_array_equal(nat, py.pixels)


class TestNativeBatchLoader:
    def test_batch_pads_and_flags(self, tmp_path):
        paths = []
        shapes = [(64, 48), (100, 100), (32, 80)]
        for i, (h, w) in enumerate(shapes):
            p = tmp_path / f"{i}.dcm"
            _write_slice(p, h=h, w=w, seed=i)
            paths.append(p)
        bad = tmp_path / "bad.dcm"
        bad.write_bytes(b"garbage")
        paths.insert(2, bad)

        pixels, dims, ok, errs = native.load_batch_native(
            paths, canvas=128, min_dim=16, threads=4
        )
        assert errs[2] == 2  # DICOM parse failed
        assert errs[0] == 0
        assert pixels.shape == (4, 128, 128)
        assert list(ok) == [True, True, False, True]
        np.testing.assert_array_equal(dims[0], [64, 48])
        np.testing.assert_array_equal(dims[3], [32, 80])
        # padded region is zero; content matches the Python reader
        ref = read_dicom(paths[0]).pixels
        np.testing.assert_array_equal(pixels[0, :64, :48], ref)
        assert pixels[0, 64:, :].sum() == 0
        assert pixels[2].sum() == 0  # failed slot left zeroed

    def test_min_dim_and_canvas_guards(self, tmp_path):
        small = tmp_path / "small.dcm"
        _write_slice(small, h=8, w=8)
        big = tmp_path / "big.dcm"
        _write_slice(big, h=300, w=300)
        okp = tmp_path / "ok.dcm"
        _write_slice(okp, h=64, w=64)
        _, _, ok, errs = native.load_batch_native(
            [small, big, okp], canvas=256, min_dim=16, threads=2
        )
        assert list(ok) == [False, False, True]
        assert errs[0] == 3 and errs[1] == 4  # too small / exceeds canvas

    def test_empty_batch(self):
        pixels, dims, ok, _ = native.load_batch_native([], canvas=64, min_dim=16)
        assert pixels.shape == (0, 64, 64) and ok.shape == (0,)


class TestNativeJpeg:
    def test_roundtrip_psnr(self):
        img = (phantom_slice(128, 128, seed=3) * 255).clip(0, 255).astype(np.uint8)
        data = native.encode_jpeg_gray(img, quality=90)
        assert data[:2] == b"\xff\xd8" and data[-2:] == b"\xff\xd9"

        from PIL import Image
        import io

        dec = np.asarray(Image.open(io.BytesIO(data)).convert("L"), np.float64)
        mse = np.mean((dec - img.astype(np.float64)) ** 2)
        psnr = 10 * np.log10(255.0**2 / max(mse, 1e-9))
        assert psnr > 30.0, f"PSNR {psnr:.1f} dB too low"

    def test_non_multiple_of_8_dims(self):
        img = np.linspace(0, 255, 61 * 45).reshape(61, 45).astype(np.uint8)
        data = native.encode_jpeg_gray(img, quality=75)
        from PIL import Image
        import io

        dec = Image.open(io.BytesIO(data))
        assert dec.size == (45, 61)

    def test_quality_orders_size(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 255, (96, 96)).astype(np.uint8)
        lo = native.encode_jpeg_gray(img, quality=20)
        hi = native.encode_jpeg_gray(img, quality=95)
        assert len(lo) < len(hi)

    def test_flat_image(self):
        img = np.full((40, 40), 128, np.uint8)
        data = native.encode_jpeg_gray(img, quality=90)
        from PIL import Image
        import io

        dec = np.asarray(Image.open(io.BytesIO(data)).convert("L"))
        assert np.abs(dec.astype(int) - 128).max() <= 2

    def test_rejects_bad_input(self):
        with pytest.raises(ValueError):
            native.encode_jpeg_gray(np.zeros((4, 4), np.float32))


class TestNativeRunnerIntegration:
    def test_parallel_native_equals_python_decode(self, tmp_path):
        """The C++ batch decoder must be bit-identical to the Python path."""
        import hashlib

        from nm03_capstone_project_tpu.cli.runner import CohortProcessor
        from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        cfg = PipelineConfig(canvas=128, render_size=128)
        root = tmp_path / "cohort"
        write_synthetic_cohort(root, n_patients=1, n_slices=4, height=128, width=120)

        def digest(out_root):
            h = hashlib.sha256()
            for p in sorted(out_root.rglob("*.jpg")):
                h.update(p.name.encode())
                h.update(p.read_bytes())
            return h.hexdigest()

        nat = CohortProcessor(
            root, tmp_path / "nat", cfg=cfg,
            batch_cfg=BatchConfig(batch_size=3, io_workers=2, use_native=True),
            mode="parallel",
        )
        assert nat.process_all_patients().succeeded_slices == 4
        py = CohortProcessor(
            root, tmp_path / "py", cfg=cfg,
            batch_cfg=BatchConfig(batch_size=3, io_workers=2, use_native=False),
            mode="parallel",
        )
        assert py.process_all_patients().succeeded_slices == 4
        assert digest(tmp_path / "nat") == digest(tmp_path / "py")

    @staticmethod
    def _write_baseline_jpeg_dicom(path, img_u8):
        """A baseline-JPEG (1.2.840.10008.1.2.4.50) file: the one compressed
        syntax the C++ parser still rejects, so it MUST drive the runner's
        per-slice Python retry."""
        import io
        import struct as st

        from PIL import Image

        from nm03_capstone_project_tpu.data.dicomlite import (
            _element,
            _encapsulate,
            JPEG_BASELINE,
        )

        buf = io.BytesIO()
        Image.fromarray(img_u8, "L").save(buf, "JPEG", quality=100)
        meta_elems = _element(0x0002, 0x0010, b"UI", JPEG_BASELINE.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", st.pack("<I", len(meta_elems)))
            + meta_elems
        )
        h, w = img_u8.shape
        ds = (
            _element(0x0028, 0x0002, b"US", st.pack("<H", 1))
            + _element(0x0028, 0x0010, b"US", st.pack("<H", h))
            + _element(0x0028, 0x0011, b"US", st.pack("<H", w))
            + _element(0x0028, 0x0100, b"US", st.pack("<H", 8))
            + _element(0x0028, 0x0103, b"US", st.pack("<H", 0))
            + st.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + st.pack("<I", 0xFFFFFFFF)
            + _encapsulate(buf.getvalue())
        )
        path.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)

    def test_native_batch_falls_back_to_python_for_compressed(self, tmp_path):
        """A batch mixing native-decodable slices (plain, RLE — both on the
        C++ fast path) with a baseline-JPEG slice (C++ rejects, code 2)
        must repair the failed slot through the Python reader's retry pool
        instead of failing the slice."""
        from nm03_capstone_project_tpu.cli.runner import CohortProcessor
        from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
        from nm03_capstone_project_tpu.data.dicomlite import (
            read_dicom,
            RLE_LOSSLESS,
        )

        cfg = PipelineConfig(canvas=128, render_size=128)
        root = tmp_path / "cohort" / "PGBM-0001" / "1-series"
        root.mkdir(parents=True)
        rng = np.random.default_rng(3)
        want = {}
        for i, ts in enumerate([None, RLE_LOSSLESS]):
            img = rng.integers(0, 4000, size=(100, 100)).astype(np.uint16)
            kw = {"transfer_syntax": ts} if ts else {}
            write_dicom(root / f"1-{i + 1:02d}.dcm", img, **kw)
            want[f"1-{i + 1:02d}"] = img.astype(np.float32)
        jb = rng.integers(0, 256, size=(100, 100)).astype(np.uint8)
        self._write_baseline_jpeg_dicom(root / "1-03.dcm", jb)
        # the retried slice's ground truth is whatever the Python reader
        # yields (baseline JPEG is lossy)
        want["1-03"] = read_dicom(root / "1-03.dcm").pixels
        proc = CohortProcessor(
            tmp_path / "cohort", tmp_path / "out", cfg=cfg,
            batch_cfg=BatchConfig(batch_size=3, io_workers=2, use_native=True),
            mode="parallel",
        )
        batch = proc._decode_batch_native(
            sorted(root.glob("*.dcm")), pad_to=3
        )
        assert batch["bad"] == []
        assert batch["stems"] == sorted(want)
        for i, stem in enumerate(batch["stems"]):
            np.testing.assert_array_equal(
                batch["pixels"][i, :100, :100], want[stem]
            )
            # padding stays zeroed around the retried slice too — below AND
            # to the right (a wrong row stride would spill rightward only)
            assert batch["pixels"][i, 100:, :].sum() == 0
            assert batch["pixels"][i, :100, 100:].sum() == 0
            assert tuple(batch["dims"][i]) == (100, 100)
