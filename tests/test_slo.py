"""The SLO plane + remote debug pulls (ISSUE 14).

Four layers, cheapest first:

* jax-free units: :class:`SLOObjective` validation and the
  :class:`SLOMonitor` burn-rate/budget math against a hand-built
  registry with an injectable clock (window forgetting, latency bucket
  rounding, probe exclusion, gauges-exist-at-construction);
* the ``nm03-loadgen --expect-slo`` client-side gate (spec parsing +
  verdict math, red and green);
* ``utils.profiling.capture_profile`` (a real ``jax.profiler`` capture
  on CPU: zip round-trip, duration clamps, one-at-a-time);
* an in-process warmed ``nm03-serve`` replica with a declared SLO: the
  ``/readyz`` ``slo``/``clock`` blocks, the ``slo_*`` gauges on
  ``/metrics.json``, probe-request exclusion end to end
  (``X-Nm03-Probe`` → ``status="probe"``, histograms untouched, trace
  kept), the ``/debug/flightrec`` + ``/debug/profile`` pulls, and the
  ``nm03-fleet flightrec``/``profile`` fan-out CLI against it.
"""

from __future__ import annotations

import base64
import io
import json
import os
import urllib.error
import urllib.request
import zipfile

import pytest

from nm03_capstone_project_tpu.obs.metrics import (
    SLO_BURN_RATE_FAST,
    SLO_BURN_RATE_SLOW,
    SLO_ERROR_BUDGET_REMAINING,
    SLO_OBJECTIVE_INFO,
    MetricsRegistry,
)
from nm03_capstone_project_tpu.obs.slo import (
    SLOMonitor,
    SLOObjective,
    objective_from_args,
)

CANVAS = 128


# -- the objective -----------------------------------------------------------


class TestSLOObjective:
    def test_budgets(self):
        obj = SLOObjective(99.5, latency_target_s=0.5)
        assert obj.availability_budget == pytest.approx(0.005)
        assert obj.latency_budget == pytest.approx(0.01)
        d = obj.describe()
        assert d["availability_pct"] == 99.5
        assert d["latency_target_ms"] == 500.0

    def test_validation(self):
        with pytest.raises(ValueError):
            SLOObjective(0.0)
        with pytest.raises(ValueError):
            SLOObjective(100.0)
        with pytest.raises(ValueError):
            SLOObjective(99.0, latency_target_s=-1)
        with pytest.raises(ValueError):
            SLOObjective(99.0, latency_pct=100.0)
        with pytest.raises(ValueError):
            SLOObjective(99.0, window_fast_s=600, window_slow_s=60)

    def test_objective_from_args(self):
        from types import SimpleNamespace

        assert objective_from_args(SimpleNamespace()) is None
        obj = objective_from_args(
            SimpleNamespace(slo_availability=None, slo_p99_ms=250.0)
        )
        assert obj.availability_pct == 99.0  # the default rides along
        assert obj.latency_target_s == pytest.approx(0.25)
        obj = objective_from_args(
            SimpleNamespace(
                slo_availability=99.9, slo_p99_ms=None,
                slo_fast_window_s=30.0, slo_slow_window_s=600.0,
            )
        )
        assert obj.latency_target_s is None
        assert obj.window_fast_s == 30.0 and obj.window_slow_s == 600.0


# -- the monitor -------------------------------------------------------------


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _mk_monitor(
    reg, clock, availability=99.0, latency_s=0.5, fast=30.0, slow=600.0
):
    return SLOMonitor(
        reg,
        SLOObjective(
            availability, latency_target_s=latency_s,
            window_fast_s=fast, window_slow_s=slow,
        ),
        "serving_requests_total",
        "serving_request_seconds",
        clock=clock,
    )


def _traffic(reg, ok=0, error=0, probe=0, latencies=()):
    if ok:
        reg.counter("serving_requests_total", status="ok").inc(ok)
    if error:
        reg.counter("serving_requests_total", status="error").inc(error)
    if probe:
        reg.counter("serving_requests_total", status="probe").inc(probe)
    h = reg.histogram("serving_request_seconds", buckets=(0.1, 0.5, 1.0))
    for v in latencies:
        h.observe(v)


class TestSLOMonitor:
    def test_gauges_exist_at_construction(self):
        reg = MetricsRegistry()
        _mk_monitor(reg, _Clock())
        assert reg.get(SLO_ERROR_BUDGET_REMAINING).value == 1.0
        assert reg.get(SLO_BURN_RATE_FAST).value == 0.0
        assert reg.get(SLO_BURN_RATE_SLOW).value == 0.0
        info = [m for m in reg.series() if m.name == SLO_OBJECTIVE_INFO]
        assert len(info) == 1 and info[0].value == 1.0
        assert info[0].labels["availability_pct"] == "99.0"
        assert info[0].labels["latency_target_ms"] == "500.0"

    def test_no_traffic_burn_zero_budget_full(self):
        reg = MetricsRegistry()
        clock = _Clock()
        mon = _mk_monitor(reg, clock)
        clock.t = 10.0
        block = mon.publish()
        assert block["burn_rate_fast"] == 0.0
        assert block["burn_rate_slow"] == 0.0
        assert block["error_budget_remaining"] == 1.0

    def test_availability_and_latency_burn_math(self):
        reg = MetricsRegistry()
        clock = _Clock()
        mon = _mk_monitor(reg, clock)
        # 1% errors against a 1% budget = availability burn exactly 1.0;
        # 2% slow (> 0.5s) against a 1% latency budget = burn 2.0 — the
        # combined burn is the max of the two SLIs
        _traffic(reg, ok=99, error=1, latencies=[0.05] * 98 + [0.9, 0.9])
        clock.t = 10.0
        block = mon.publish()
        assert block["burn_rate_fast"] == pytest.approx(2.0)
        assert block["burn_rate_slow"] == pytest.approx(2.0)
        # budget: latency consumed 2/(0.01*100) = 2 -> remaining -1
        assert block["error_budget_remaining"] == pytest.approx(-1.0)

    def test_latency_target_rounds_up_to_bucket_bound(self):
        reg = MetricsRegistry()
        clock = _Clock()
        # target 0.3 sits between the 0.1 and 0.5 bounds: slow = above
        # 0.5 (rounded UP), so a 0.4s request is not counted slow
        mon = _mk_monitor(reg, clock, latency_s=0.3)
        _traffic(reg, ok=100, latencies=[0.4] * 99 + [0.9])
        clock.t = 5.0
        block = mon.publish()
        assert block["burn_rate_fast"] == pytest.approx(1.0)  # 1% > 0.5s

    def test_fast_window_forgets_old_badness_slow_remembers(self):
        reg = MetricsRegistry()
        clock = _Clock()
        mon = _mk_monitor(reg, clock, fast=30.0, slow=600.0)
        _traffic(reg, ok=90, error=10, latencies=[0.05] * 100)
        clock.t = 10.0
        assert mon.publish()["burn_rate_fast"] == pytest.approx(10.0)
        # a quiet hour later, fresh clean traffic: the fast window only
        # sees the clean delta, the slow window still holds the incident
        clock.t = 200.0
        mon.publish()  # a baseline sample inside the coming fast window
        _traffic(reg, ok=100, latencies=[0.05] * 100)
        clock.t = 220.0
        block = mon.publish()
        assert block["burn_rate_fast"] == pytest.approx(0.0)
        assert block["burn_rate_slow"] == pytest.approx(5.0)  # 10/200 req
        # the budget is lifetime: 10 errors / (1% of 200) = 5 consumed
        assert block["error_budget_remaining"] == pytest.approx(-4.0)

    def test_probe_status_is_excluded(self):
        reg = MetricsRegistry()
        clock = _Clock()
        mon = _mk_monitor(reg, clock)
        # 100 probes and nothing else: no traffic as far as the SLO is
        # concerned — probes are in neither the good nor the bad set
        _traffic(reg, probe=100)
        clock.t = 10.0
        block = mon.publish()
        assert block["burn_rate_fast"] == 0.0
        assert block["error_budget_remaining"] == 1.0

    def test_availability_only_objective_ignores_latency(self):
        reg = MetricsRegistry()
        clock = _Clock()
        mon = _mk_monitor(reg, clock, latency_s=None)
        _traffic(reg, ok=100, latencies=[0.9] * 100)  # all "slow" — no SLI
        clock.t = 10.0
        block = mon.publish()
        assert block["burn_rate_fast"] == 0.0
        assert block["error_budget_remaining"] == 1.0


# -- the loadgen gate --------------------------------------------------------


class TestLoadgenSLOGate:
    def test_parse_spec(self):
        from nm03_capstone_project_tpu.serving.loadgen import parse_slo_spec

        assert parse_slo_spec("availability=99.5,p99_ms=500") == {
            "availability": 99.5, "p99_ms": 500.0,
        }
        assert parse_slo_spec("p99_ms=250") == {"p99_ms": 250.0}
        for bad in ("", "latency=1", "availability=abc", "availability=0",
                    "availability=101"):
            with pytest.raises(ValueError):
                parse_slo_spec(bad)

    def test_evaluate_green_and_red(self):
        from nm03_capstone_project_tpu.serving.loadgen import evaluate_slo

        summary = {
            "requests_total": 100, "requests_ok": 99,
            "latency_ms": {"p99": 450.0},
        }
        gate = evaluate_slo(
            summary, {"availability": 99.0, "p99_ms": 500.0}
        )
        assert gate["pass"] is True
        assert gate["checks"]["availability"]["observed_pct"] == 99.0
        # red: availability floor missed
        gate = evaluate_slo(summary, {"availability": 99.5})
        assert gate["pass"] is False
        # red: p99 target exceeded
        gate = evaluate_slo(summary, {"p99_ms": 400.0})
        assert gate["pass"] is False
        # red: no latency measured at all cannot pass a latency gate
        gate = evaluate_slo(
            {"requests_total": 0, "requests_ok": 0, "latency_ms": {}},
            {"p99_ms": 400.0},
        )
        assert gate["pass"] is False

    def test_cli_rejects_malformed_spec(self):
        from nm03_capstone_project_tpu.serving import loadgen

        with pytest.raises(SystemExit):
            loadgen.main(["--expect-slo", "nonsense", "--requests", "1"])

    def test_serve_clis_reject_bad_slo_flags_as_usage_errors(self, capsys):
        """A bad --slo-* value is an argparse usage error (exit 2), never
        a mid-startup traceback or a silently-swallowed default (review
        fix)."""
        from nm03_capstone_project_tpu.fleet import cli as fleet_cli
        from nm03_capstone_project_tpu.serving import server

        for argv in (["--slo-availability", "100"],
                     ["--slo-availability", "99", "--slo-fast-window-s", "0"]):
            with pytest.raises(SystemExit) as exc:
                server.main(argv)
            assert exc.value.code == 2
        with pytest.raises(SystemExit) as exc:
            fleet_cli.main([
                "serve", "--replicas", "h:1", "--slo-availability", "0",
            ])
        assert exc.value.code == 2
        capsys.readouterr()  # swallow the usage chatter

    def test_last_block_reuses_the_published_verdict(self):
        reg = MetricsRegistry()
        clock = _Clock()
        mon = _mk_monitor(reg, clock)
        clock.t = 5.0
        block = mon.publish()
        n_samples = len(mon._samples)
        assert mon.last_block() is block  # no re-sampling
        assert len(mon._samples) == n_samples
        # a never-published monitor publishes once on demand
        mon2 = _mk_monitor(MetricsRegistry(), clock)
        assert mon2.last_block()["error_budget_remaining"] == 1.0


# -- the profiler capture ----------------------------------------------------


class TestCaptureProfile:
    def test_capture_round_trip(self):
        from nm03_capstone_project_tpu.utils.profiling import capture_profile

        out = capture_profile(60)
        assert out["duration_ms"] == 60
        assert isinstance(out["files"], list) and out["files"]
        data = base64.b64decode(out["zip_b64"])
        assert out["zip_bytes"] == len(data)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            assert sorted(zf.namelist()) == sorted(
                f["name"] for f in out["files"]
            )

    def test_duration_clamps(self):
        from nm03_capstone_project_tpu.utils.profiling import capture_profile

        for bad in (0, 5, 10_001):
            with pytest.raises(ValueError):
                capture_profile(bad)

    def test_one_capture_at_a_time(self):
        from nm03_capstone_project_tpu.utils import profiling

        assert profiling._CAPTURE_LOCK.acquire(blocking=False)
        try:
            with pytest.raises(profiling.ProfileBusy):
                profiling.capture_profile(60)
        finally:
            profiling._CAPTURE_LOCK.release()

    def test_oversized_zip_kept_server_side(self):
        from nm03_capstone_project_tpu.utils.profiling import capture_profile

        out = capture_profile(60, zip_cap_bytes=1)
        assert out.get("zip_dropped") is True
        assert "zip_b64" not in out
        assert out["files"]  # the listing survives the wire cap
        # the archive itself is NOT destroyed: it lands server-side and
        # the response names it
        try:
            assert os.path.getsize(out["zip_path"]) == out["zip_bytes"]
            with zipfile.ZipFile(out["zip_path"]) as zf:
                assert zf.namelist()
        finally:
            os.unlink(out["zip_path"])


# -- the in-process replica: SLO + probe + debug endpoints -------------------


def _get(url, timeout=30.0):
    req = urllib.request.Request(url, method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(url, body, headers, timeout=60.0):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _counter_value(app, name, **labels):
    m = app.obs.registry.get(name, **labels)
    return m.value if m is not None else None


@pytest.fixture(scope="module")
def slo_served():
    """One warmed loopback replica with a declared SLO (1 compile)."""
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.serving.server import (
        ServingApp,
        serve_in_thread,
    )

    app = ServingApp(
        cfg=PipelineConfig(canvas=CANVAS),
        queue_capacity=16,
        buckets=(1,),
        max_wait_s=0.01,
        request_timeout_s=60.0,
        lanes=1,
        slo=SLOObjective(99.0, latency_target_s=30.0, window_fast_s=30.0,
                         window_slow_s=600.0),
    )
    httpd, _, port = serve_in_thread(app)
    yield app, f"http://127.0.0.1:{port}"
    app.begin_drain(reason="test_teardown")
    httpd.shutdown()
    httpd.server_close()
    app.close()


def _phantom_body(h=CANVAS, w=CANVAS, seed=0):
    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    return phantom_slice(h, w, seed=seed).astype("<f4").tobytes()


def _raw_headers(h, w, **extra):
    return {
        "Content-Type": "application/octet-stream",
        "X-Nm03-Height": str(h), "X-Nm03-Width": str(w),
        **extra,
    }


class TestServingSLOAndDebug:
    def test_readyz_carries_slo_and_clock(self, slo_served):
        app, base = slo_served
        status, body = _get(base + "/readyz")
        st = json.loads(body)
        assert status == 200
        slo = st["slo"]
        assert slo["objective"]["availability_pct"] == 99.0
        assert 0.0 <= slo["error_budget_remaining"] <= 1.0
        clock = st["clock"]
        # the handshake pair is this process's clocks: the offset it
        # implies must match ours to well under a second (same host)
        import time as _time

        offset = clock["ts_unix"] - clock["mono_s"]
        assert offset == pytest.approx(
            _time.time() - _time.monotonic(), abs=5.0
        )

    def test_probe_requests_excluded_but_traced(self, slo_served):
        app, base = slo_served
        url = base + "/v1/segment?output=mask"
        body = _phantom_body()
        # settle the baseline with one REAL request first
        status, payload, _ = _post(url, body, _raw_headers(CANVAS, CANVAS))
        assert status == 200
        ok_before = _counter_value(app, "serving_requests_total", status="ok")
        hist = app.obs.registry.get("serving_request_seconds")
        hist_before = hist.count
        qwait = app.obs.registry.get("serving_queue_wait_seconds")
        qwait_before = qwait.count
        status, payload, headers = _post(
            url, body,
            _raw_headers(CANVAS, CANVAS, **{
                "X-Nm03-Probe": "1",
                "X-Nm03-Request-Id": "fleet-probe-test-1",
            }),
        )
        assert status == 200 and payload["mask_pixels"] >= 0
        assert headers["X-Nm03-Request-Id"] == "fleet-probe-test-1"
        # counted as a probe, not ok; latency histograms untouched
        assert _counter_value(
            app, "serving_requests_total", status="probe"
        ) == 1
        assert _counter_value(
            app, "serving_requests_total", status="ok"
        ) == ok_before
        assert hist.count == hist_before
        assert qwait.count == qwait_before
        # still fully traced: the serve_trace event exists, probe-flagged
        probes = [
            r for r in app.obs.events.tail
            if r["event"] == "serve_trace"
            and r.get("trace_id") == "fleet-probe-test-1"
        ]
        assert len(probes) == 1 and probes[0]["probe"] is True
        assert probes[0]["spans"]

    def test_slo_gauges_on_metrics_json(self, slo_served):
        app, base = slo_served
        status, body = _get(base + "/metrics.json")
        assert status == 200
        names = {
            m["name"]: m for m in json.loads(body)["metrics"]
            if m["name"].startswith("slo_")
        }
        assert SLO_BURN_RATE_FAST in names
        assert SLO_BURN_RATE_SLOW in names
        assert SLO_ERROR_BUDGET_REMAINING in names
        assert names[SLO_ERROR_BUDGET_REMAINING]["value"] == 1.0
        assert names[SLO_BURN_RATE_FAST]["value"] == 0.0

    def test_debug_flightrec_pull(self, slo_served):
        app, base = slo_served
        status, body = _get(base + "/debug/flightrec")
        assert status == 200
        snap = json.loads(body)
        assert snap["schema"] == "nm03.flightrec.v1"
        assert snap["reason"] == "debug_pull"
        assert snap["threads"]  # the serving threads' rings are in there

    @pytest.mark.slow
    def test_debug_profile_pull(self, slo_served):
        app, base = slo_served
        status, body = _get(base + "/debug/profile?ms=60")
        assert status == 200
        out = json.loads(body)
        assert out["duration_ms"] == 60 and out["files"]
        zipfile.ZipFile(io.BytesIO(base64.b64decode(out["zip_b64"])))
        # guards: malformed + out-of-clamp durations are 400s
        assert _get(base + "/debug/profile?ms=abc")[0] == 400
        assert _get(base + "/debug/profile?ms=1")[0] == 400

    @pytest.mark.slow
    def test_fleet_debug_pull_cli_fans_out(self, slo_served, tmp_path):
        """`nm03-fleet flightrec|profile` against a real replica plus one
        dead target: the live pull lands on disk, the dead one is a
        FAILED row, exit 1 reports the partial pull without discarding
        it."""
        from nm03_capstone_project_tpu.fleet import cli as fleet_cli

        app, base = slo_served
        out_dir = tmp_path / "pulls"
        rc = fleet_cli.main([
            "flightrec", "--replicas", base, "--out-dir", str(out_dir),
        ])
        assert rc == 0
        label = base.split("://", 1)[1].replace(":", "_")
        dump = json.loads((out_dir / f"flightrec_{label}.json").read_text())
        assert dump["schema"] == "nm03.flightrec.v1"
        rc = fleet_cli.main([
            "profile", "--replicas", base, "--ms", "60",
            "--out-dir", str(out_dir),
        ])
        assert rc == 0
        meta = json.loads((out_dir / f"profile_{label}.json").read_text())
        assert meta["duration_ms"] == 60
        assert zipfile.ZipFile(out_dir / f"profile_{label}.zip").namelist()
        # partial pull: one live + one dead target -> exit 1, live kept
        (out_dir2 := tmp_path / "partial").mkdir()
        rc = fleet_cli.main([
            "flightrec",
            "--replicas", f"{base},127.0.0.1:1",
            "--out-dir", str(out_dir2), "--timeout-s", "3",
        ])
        assert rc == 1
        assert (out_dir2 / f"flightrec_{label}.json").exists()

    def test_loadgen_expect_slo_green_and_red(self, slo_served, tmp_path):
        """The client-side gate against real traffic: a generous
        objective passes (exit 0, slo_gate in the artifact), an
        impossible p99 fails (exit 1)."""
        from nm03_capstone_project_tpu.serving import loadgen

        app, base = slo_served
        results = tmp_path / "lg.json"
        rc = loadgen.main([
            "--url", base, "--requests", "6", "--concurrency", "2",
            "--warmup", "0", "--height", str(CANVAS), "--width", str(CANVAS),
            "--expect-slo", "availability=99.0,p99_ms=60000",
            "--results-json", str(results),
        ])
        assert rc == 0
        gate = json.loads(results.read_text())["slo_gate"]
        assert gate["pass"] is True
        assert gate["checks"]["availability"]["observed_pct"] == 100.0
        rc = loadgen.main([
            "--url", base, "--requests", "4", "--concurrency", "2",
            "--warmup", "0", "--height", str(CANVAS), "--width", str(CANVAS),
            "--expect-slo", "p99_ms=0.001",
        ])
        assert rc == 1
