"""Donation / aliasing correctness (SURVEY.md section 5).

The reference needed mutexes and a parallel-compute/serial-export split to
stay race-free; a functional pipeline's analog hazards are buffer donation
and unintended aliasing. These tests pin: donation does not change results,
a donated buffer is actually invalidated (not silently copied), and the
compiled pipeline is pure (same input -> bit-identical output).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nm03_capstone_project_tpu.cli.runner import _compiled_batch_fn
from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.core import pad_to_canvas
from nm03_capstone_project_tpu.data.synthetic import phantom_series
from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

CFG = PipelineConfig(canvas=64, grow_block_iters=8, grow_max_iters=128)


def _batch(n=3, seed=4):
    b = pad_to_canvas(phantom_series(n, 64, 64, seed=seed), CFG.canvas_hw)
    return jnp.asarray(b.pixels), jnp.asarray(b.dims)


class TestPurity:
    def test_same_input_twice_is_bit_identical(self):
        px, dm = _batch()
        f = jax.jit(lambda p, d: process_batch(p, d, CFG)["mask"])
        a = np.asarray(f(px, dm))
        b = np.asarray(f(px, dm))
        np.testing.assert_array_equal(a, b)

    def test_input_buffer_not_mutated(self):
        px, dm = _batch()
        before = np.asarray(px).copy()
        jax.jit(lambda p, d: process_batch(p, d, CFG)["mask"])(px, dm)
        np.testing.assert_array_equal(np.asarray(px), before)


class TestDonation:
    @pytest.mark.slow
    def test_donated_batch_fn_matches_undonated(self):
        px, dm = _batch()
        donated = _compiled_batch_fn(CFG)  # donate_argnums=(0,)
        # reference result from an undonated jit of the same program
        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice
        from nm03_capstone_project_tpu.render.render import (
            render_gray,
            render_segmentation,
        )

        def one(pixels, dims):
            out = process_slice(pixels, dims, CFG)
            orig = render_gray(out["original"], dims, CFG.render_size)
            proc = render_segmentation(
                out["mask"], dims, CFG.render_size, CFG.overlay_opacity,
                CFG.overlay_border_opacity, CFG.overlay_border_radius,
            )
            return orig, proc

        ref = jax.jit(jax.vmap(one))
        ro, rp = ref(px, dm)
        px2, dm2 = _batch()  # fresh buffers to donate
        do, dp, _conv = donated(px2, dm2)
        np.testing.assert_array_equal(np.asarray(do), np.asarray(ro))
        np.testing.assert_array_equal(np.asarray(dp), np.asarray(rp))

    @pytest.mark.slow
    def test_donated_buffer_is_consumed(self):
        px, dm = _batch()
        donated = _compiled_batch_fn(CFG)
        donated(px, dm)
        # the donated pixel stack must be invalidated, not aliased or copied
        if jax.default_backend() == "cpu":
            pytest.skip("XLA:CPU does not implement input donation")
        with pytest.raises(RuntimeError):
            np.asarray(px)
