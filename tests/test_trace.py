"""ISSUE 7 tests: request-scoped tracing, compile-cost accounting, and the
crash flight recorder.

Four layers, mirroring the serving suites' structure:

* jax-free units: trace ids, span records, shared chunk spans, the Chrome
  ``trace_event`` exporter, the flight recorder's rings and atomic dumps;
* the validator loop: ``nm03-trace`` CLI -> ``check_telemetry.py
  --expect-trace`` (green on a real export, red on torn B/E pairs and on
  spans missing trace ids);
* in-process serving: trace ids honored/echoed, span trees in the event
  stream, compile-cost in ``/readyz`` and the metrics snapshot, the
  hang->degradation auto-dump drill;
* subprocess acceptance: ``nm03-serve --lanes 4`` under loadgen traffic
  produces a Perfetto-loadable trace where a coalesced batch shows >=2
  requests sharing a dispatch span and dispatches land on >=2 distinct
  lanes; and the SIGUSR2 drill — a live server with an in-flight (hung)
  request dumps a flight record carrying that request's trace id.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from nm03_capstone_project_tpu.obs import flightrec, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


# -- jax-free units ----------------------------------------------------------


class TestTraceIds:
    def test_sanitize_accepts_sane_ids(self):
        for ok in ("abc", "lg-1a2b3c-000001", "A.b:c_d-9"):
            assert trace.sanitize_trace_id(ok) == ok

    def test_sanitize_rejects_garbage(self):
        for bad in (None, "", "  ", "a" * 65, "sp ace", "new\nline",
                    "-leading", 'q"uote', b"bytes"):
            assert trace.sanitize_trace_id(bad) is None

    def test_new_ids_unique(self):
        ids = {trace.new_trace_id() for _ in range(64)}
        assert len(ids) == 64

    def test_span_ids_pid_salted(self):
        # the exporter dedupes shared spans by id: ids from two processes
        # (concatenated replica logs, ">>"-appended restarts) must not
        # collide, so the per-process counter is salted with the pid
        import os

        sid = trace._new_span_id()
        assert sid.startswith(f"s{os.getpid():x}.")


class TestSpans:
    def test_add_span_and_context_manager(self):
        ctx = trace.TraceContext("t1")
        ctx.add_span("queue_wait", 1.0, 1.25, extra="x")
        with ctx.span("encode"):
            pass
        spans = ctx.snapshot()
        assert [s["name"] for s in spans] == ["queue_wait", "encode"]
        assert spans[0]["dur_s"] == 0.25 and spans[0]["trace_ids"] == ["t1"]
        assert spans[0]["extra"] == "x"
        assert spans[1]["dur_s"] >= 0

    def test_fields_cannot_shadow_the_span_envelope(self):
        rec = trace.make_span("x", 0.0, 1.0, ["t"], **{"riders": 99, "ok": 1})
        assert rec["riders"] == 1  # reserved keys win over caller fields
        assert rec["ok"] == 1

    def test_chunk_span_shared_across_riders(self):
        a, b = trace.TraceContext("a"), trace.TraceContext("b")
        chunk = trace.ChunkTrace([a, b], lane=2)
        with chunk.span("device_dispatch", attempt=1):
            pass
        sa, sb = a.snapshot()[0], b.snapshot()[0]
        assert sa is sb  # literally one record, many riders
        assert sa["riders"] == 2 and sa["lane"] == 2
        assert sorted(sa["trace_ids"]) == ["a", "b"]

    def test_null_trace_is_inert(self):
        with trace.NULL_TRACE.span("anything"):
            pass
        trace.NULL_TRACE.mark("anything")


class TestChromeExport:
    def _records(self):
        a = trace.TraceContext("ra")
        a.add_span("queue_wait", 5.0, 5.1)
        b = trace.TraceContext("rb")
        b.add_span("queue_wait", 5.05, 5.1)
        chunk = trace.ChunkTrace([a, b], lane=0)
        with chunk.span("device_dispatch", attempt=1):
            time.sleep(0.002)
        return [
            {"event": "serve_trace", "trace_id": "ra", "spans": a.snapshot()},
            {"event": "serve_trace", "trace_id": "rb", "spans": b.snapshot()},
        ]

    def test_be_pairs_dedupe_and_order(self):
        events = trace.chrome_trace_events(self._records())
        bs = [e for e in events if e.get("ph") == "B"]
        es = [e for e in events if e.get("ph") == "E"]
        # 2 queue_waits + ONE shared dispatch (deduped by span id)
        assert len(bs) == len(es) == 3
        ts = [e["ts"] for e in events if e.get("ph") in ("B", "E")]
        assert ts == sorted(ts)
        disp = [e for e in bs if e["name"] == "device_dispatch"]
        assert disp[0]["args"]["riders"] == 2
        assert sorted(disp[0]["args"]["trace_ids"]) == ["ra", "rb"]

    def test_track_layout(self):
        events = trace.chrome_trace_events(self._records())
        names = {
            e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        assert "lane 0" in names
        assert any(n.startswith("req ") for n in names)

    def test_every_b_event_carries_trace_ids(self):
        events = trace.chrome_trace_events(self._records())
        for e in events:
            if e.get("ph") == "B":
                assert e["args"]["trace_ids"], e

    def test_reused_client_trace_id_gets_distinct_tracks(self):
        # trace ids are client-controlled; a retry reusing one mid-flight
        # must not let the serializing cursor rewrite either request's
        # times — the two span trees get distinct request tracks
        recs = []
        for req_id in ("srv-1", "srv-2"):
            ctx = trace.TraceContext("dup-id")
            ctx.add_span("queue_wait", 1.0, 1.2)
            recs.append({
                "event": "serve_trace", "trace_id": "dup-id",
                "request_id": req_id, "spans": ctx.snapshot(),
            })
        events = trace.chrome_trace_events(recs)
        tracks = {
            e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        assert "req dup-id (srv-1)" in tracks and "req dup-id (srv-2)" in tracks
        # and the overlapping spans keep their true (untouched) start ts
        bs = [e for e in events if e.get("ph") == "B"]
        assert len(bs) == 2 and len({e["tid"] for e in bs}) == 2
        assert all(e["ts"] == 1.0 * 1e6 for e in bs)

    def test_genuine_lane_overlap_spills_to_sibling_track(self):
        # a PR-3 retry ladder: attempt 1 abandoned at the deadline but
        # still running while attempt 2 serves the batch — BOTH spans land
        # on "lane 0". The serializing cursor must not rewrite attempt 2's
        # start or zero-width it; real overlap spills to a sibling track
        # with true times, and the export still validates
        ctx = trace.TraceContext("rc")
        a1 = trace.make_span(
            "device_dispatch", 1.0, 3.6, ["rc"], lane=0, attempt=1
        )
        a2 = trace.make_span(
            "device_dispatch", 2.0, 2.5, ["rc"], lane=0, attempt=2
        )
        ctx.add(a1)
        ctx.add(a2)
        events = trace.chrome_trace_events(
            [{"event": "serve_trace", "trace_id": "rc",
              "spans": ctx.snapshot()}]
        )
        bs = {e["args"]["attempt"]: e for e in events if e.get("ph") == "B"}
        assert bs[1]["ts"] == 1.0 * 1e6 and bs[2]["ts"] == 2.0 * 1e6
        assert bs[1]["tid"] != bs[2]["tid"]
        tracks = {
            e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        assert "lane 0" in tracks and "lane 0 (overlap)" in tracks
        # per-track stacks still balance: E never precedes its B
        for tid in {e["tid"] for e in events if e.get("ph") in "BE"}:
            depth = 0
            for e in events:
                if e.get("tid") != tid or e.get("ph") not in "BE":
                    continue
                depth += 1 if e["ph"] == "B" else -1
                assert depth >= 0
            assert depth == 0

    def test_schema_drifted_record_does_not_crash_export(self):
        # a null trace_id + present-but-EMPTY trace_ids list (hand-edited
        # or foreign-producer stream) must export, not IndexError
        events = trace.chrome_trace_events([
            {"event": "serve_trace", "trace_id": None, "spans": [
                {"id": "s1", "name": "x", "t0_s": 1.0, "dur_s": 0.1,
                 "lane": None, "trace_ids": []},
            ]},
        ])
        assert any(e.get("ph") == "B" for e in events)


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = flightrec.FlightRecorder(ring=8)
        for i in range(50):
            rec.note("span", f"n{i}")
        snap = rec.snapshot()
        (records,) = snap["threads"].values()
        assert len(records) == 8 and records[-1]["name"] == "n49"

    def test_thread_table_lru_capped(self):
        rec = flightrec.FlightRecorder(max_threads=2)

        def noter(i):
            rec.note("span", f"from{i}")

        for i in range(4):
            t = threading.Thread(target=noter, args=(i,), name=f"ring-t{i}")
            t.start()
            t.join()
        snap = rec.snapshot()
        assert len(snap["threads"]) == 2
        names = {k.split("#")[0] for k in snap["threads"]}
        assert names == {"ring-t2", "ring-t3"}

    def test_rings_are_per_thread_even_with_shared_names(self):
        # every supervisor worker is named "nm03-dispatch": one shared
        # ring would let healthy lanes flush a wedged lane's evidence
        rec = flightrec.FlightRecorder(ring=4)
        barrier = threading.Barrier(2)

        def noter(tag):
            barrier.wait(timeout=10)
            for i in range(4):
                rec.note("span", f"{tag}-{i}")

        threads = [
            threading.Thread(target=noter, args=(t,), name="nm03-dispatch")
            for t in ("a", "b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = rec.snapshot()
        assert len(snap["threads"]) == 2  # distinct idents, distinct rings
        assert snap["records_total"] == 8  # nothing overwrote anything

    def test_eviction_spares_live_silent_threads(self):
        # a wedged thread stops noting (so stops being LRU-refreshed);
        # eviction must drop dead threads' rings before a live one's
        rec = flightrec.FlightRecorder(max_threads=2)
        hold = threading.Event()
        parked = threading.Event()

        def wedged():
            rec.note("span_begin", "device_dispatch", trace_ids=["stuck-1"])
            parked.set()
            hold.wait(timeout=30)

        w = threading.Thread(target=wedged, name="wedged-lane")
        w.start()
        assert parked.wait(timeout=10)
        try:
            for i in range(5):  # transient handler-thread churn
                t = threading.Thread(
                    target=lambda: rec.note("span", "encode"),
                    name=f"handler-{i}",
                )
                t.start()
                t.join()
            snap = rec.snapshot()
            wedged_rings = [k for k in snap["threads"] if "wedged-lane" in k]
            assert wedged_rings, snap["threads"].keys()
            assert "stuck-1" in json.dumps(snap["threads"][wedged_rings[0]])
        finally:
            hold.set()
            w.join(timeout=10)

    def test_dump_is_atomic_and_schema_stable(self, tmp_path):
        rec = flightrec.FlightRecorder()
        rec.note("span", "queue_wait", trace_id="abc", lane=0)
        path = rec.dump(path=str(tmp_path / "d.json"), reason="unit")
        assert not list(tmp_path.glob("*.tmp"))  # tmp renamed away
        data = json.loads((tmp_path / "d.json").read_text())
        assert data["schema"] == flightrec.SCHEMA_FLIGHT
        assert data["reason"] == "unit" and data["records_total"] == 1
        assert "abc" in json.dumps(data["threads"])
        assert path == str(tmp_path / "d.json")

    def test_auto_dump_inert_until_configured(self, tmp_path):
        rec = flightrec.FlightRecorder()
        rec.note("span", "x")
        assert rec.auto_dump("nope") is None
        rec.configure(str(tmp_path))
        path = rec.auto_dump("armed")
        assert path is not None and os.path.exists(path)
        assert "armed" in os.path.basename(path)
        rec.configure(None)
        assert rec.auto_dump("again") is None

    def test_note_never_raises(self):
        rec = flightrec.FlightRecorder()
        rec.note("span", "x", unserializable=object())  # stored as-is, fine
        # dump stringifies via default=str rather than dying
        snap = rec.snapshot()
        assert snap["records_total"] == 1


# -- the exporter -> validator loop ------------------------------------------


def run_checker(*args):
    return subprocess.run(
        [sys.executable, CHECKER, *[str(a) for a in args]],
        capture_output=True, text=True, timeout=60,
    )


class TestExpectTraceGate:
    def _export(self, tmp_path):
        ctx = trace.TraceContext("ok-1")
        ctx.add_span("queue_wait", 1.0, 1.1)
        chunk = trace.ChunkTrace([ctx], lane=0)
        with chunk.span("device_dispatch", attempt=1):
            pass
        events = tmp_path / "e.jsonl"
        with open(events, "w") as f:
            f.write(json.dumps({"event": "run_started"}) + "\n")
            f.write(json.dumps({
                "event": "serve_trace", "trace_id": "ok-1",
                "spans": ctx.snapshot(),
            }) + "\n")
        out = tmp_path / "t.json"
        n = trace.export_chrome_trace(str(events), str(out))
        assert n == 1
        return out

    def test_valid_export_passes(self, tmp_path):
        out = self._export(tmp_path)
        res = run_checker("--expect-trace", out)
        assert res.returncode == 0, res.stderr

    def test_unbalanced_pairs_fail(self, tmp_path):
        out = self._export(tmp_path)
        data = json.loads(out.read_text())
        data["traceEvents"] = [
            e for e in data["traceEvents"] if e.get("ph") != "E"
        ]
        out.write_text(json.dumps(data))
        res = run_checker("--expect-trace", out)
        assert res.returncode == 1
        assert "unclosed" in res.stderr

    def test_missing_trace_id_fails(self, tmp_path):
        out = self._export(tmp_path)
        data = json.loads(out.read_text())
        for e in data["traceEvents"]:
            if e.get("ph") == "B":
                e["args"] = {}
        out.write_text(json.dumps(data))
        res = run_checker("--expect-trace", out)
        assert res.returncode == 1
        assert "no trace id" in res.stderr

    def test_backwards_ts_fails(self, tmp_path):
        out = self._export(tmp_path)
        data = json.loads(out.read_text())
        be = [e for e in data["traceEvents"] if e.get("ph") in ("B", "E")]
        be[-1]["ts"] = -1.0
        out.write_text(json.dumps(data))
        res = run_checker("--expect-trace", out)
        assert res.returncode == 1
        assert "backwards" in res.stderr

    def test_empty_export_fails(self, tmp_path):
        out = tmp_path / "empty.json"
        out.write_text(json.dumps({"traceEvents": []}))
        res = run_checker("--expect-trace", out)
        assert res.returncode == 1

    def test_nm03_trace_cli_exit_codes(self, tmp_path):
        events = tmp_path / "no_traces.jsonl"
        events.write_text(json.dumps({"event": "run_started"}) + "\n")
        res = subprocess.run(
            [sys.executable, "-m", "nm03_capstone_project_tpu.obs.trace",
             str(events)],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert res.returncode == 1  # empty export is a failed export
        # diagnostics belong on stderr (runbook pipes stdout to artifacts)
        assert "no serve_trace records" in res.stderr


# -- the multi-log fleet merge (ISSUE 14) ------------------------------------


WALL0 = 1_700_000_000.0  # a fixed wall epoch shared by every fake process


def _jsonl(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def _span(name, wall_t0, dur, mono_epoch, **fields):
    """One span whose process booted at wall ``WALL0 - mono_epoch``... i.e.
    whose monotonic clock reads ``wall - (WALL0 - mono_epoch)``."""
    t0 = wall_t0 - WALL0 + mono_epoch
    return trace.make_span(name, t0, t0 + dur, fields.pop("trace_ids", ["t"]),
                           **fields)


def _envelope(event, mono_epoch, run_id="run", **fields):
    # ts_unix - mono_s must recover the process's wall offset: pick an
    # arbitrary emit moment consistent with the epoch mapping
    return {
        "event": event, "run_id": run_id,
        "ts_unix": WALL0 + 9.0, "mono_s": 9.0 + mono_epoch,
        **fields,
    }


class TestMultiLogMerge:
    """Router + N replica logs -> ONE clock-aligned multi-process export."""

    def _router_stream(self, path, replica_label, trace_id="t1",
                       mono_epoch=5000.0):
        spans = [
            _span("route_pick", WALL0 + 1.0, 0.01, mono_epoch,
                  trace_ids=[trace_id], replica=replica_label, attempt=1),
            _span("proxy_hop", WALL0 + 1.01, 0.4, mono_epoch,
                  trace_ids=[trace_id], replica=replica_label, outcome="ok",
                  attempt=1),
        ]
        _jsonl(path, [
            _envelope("run_started", mono_epoch, run_id="router"),
            _envelope("fleet_trace", mono_epoch, run_id="router",
                      trace_id=trace_id, request_id="fl-000001",
                      replica=replica_label, replica_hops=0, status=200,
                      spans=spans),
        ])

    def _replica_stream(self, path, trace_id="t1", mono_epoch=100.0,
                        run_id="replica-run"):
        spans = [
            _span("queue_wait", WALL0 + 1.02, 0.05, mono_epoch,
                  trace_ids=[trace_id]),
            _span("device_dispatch", WALL0 + 1.1, 0.2, mono_epoch,
                  trace_ids=[trace_id], lane=0),
        ]
        _jsonl(path, [
            _envelope("run_started", mono_epoch, run_id=run_id),
            _envelope("serve_trace", mono_epoch, run_id=run_id,
                      trace_id=trace_id, request_id="r1", spans=spans),
        ])

    def test_single_stream_keeps_the_classic_export(self, tmp_path):
        events = tmp_path / "solo.jsonl"
        self._replica_stream(events)
        out = tmp_path / "solo.json"
        assert trace.export_chrome_trace(str(events), str(out)) == 1
        data = json.loads(out.read_text())
        assert data["metadata"] == {"source": str(events), "requests": 1}
        assert {e["pid"] for e in data["traceEvents"]} == {1}
        proc = [e for e in data["traceEvents"]
                if e.get("name") == "process_name"]
        assert proc[0]["args"]["name"] == "nm03-serve"

    def test_merge_aligns_clocks_and_names_processes(self, tmp_path):
        """Two processes whose monotonic epochs differ by ~5000s but whose
        spans happened at the SAME wall moment land adjacent on one
        timeline, each on its own pid — the replica named by the
        trace-id join against the router's fleet_trace records."""
        router, replica = tmp_path / "router.jsonl", tmp_path / "r1.jsonl"
        self._router_stream(router, "127.0.0.1:8081")
        self._replica_stream(replica)
        out = tmp_path / "merged.json"
        n = trace.export_chrome_trace([str(router), str(replica)], str(out))
        assert n == 2
        data = json.loads(out.read_text())
        assert data["metadata"]["processes"] == 2
        names = {
            e["pid"]: e["args"]["name"] for e in data["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert set(names.values()) == {"nm03-fleet", "replica 127.0.0.1:8081"}
        by_name = {}
        for e in data["traceEvents"]:
            if e.get("ph") == "B":
                by_name[e["name"]] = e
        # wall alignment: route_pick began at WALL0+1.0, queue_wait at
        # WALL0+1.02 — 20ms apart on the merged timeline, despite the
        # ~4900s monotonic skew between the two processes
        dt_us = by_name["queue_wait"]["ts"] - by_name["route_pick"]["ts"]
        assert dt_us == pytest.approx(20_000, abs=200)
        # distinct processes, same trace id
        assert by_name["proxy_hop"]["pid"] != by_name["device_dispatch"]["pid"]
        assert by_name["proxy_hop"]["args"]["trace_ids"] == ["t1"]
        assert by_name["proxy_hop"]["args"]["replica"] == "127.0.0.1:8081"
        # the merged stream still satisfies the base trace contract AND
        # the fleet one (proxy_hop resolves across pids)
        res = run_checker("--expect-fleet-trace", out)
        assert res.returncode == 0, res.stderr

    def test_unjoinable_replica_falls_back_to_run_id(self, tmp_path):
        router, replica = tmp_path / "router.jsonl", tmp_path / "r1.jsonl"
        self._router_stream(router, "127.0.0.1:8081", trace_id="t1")
        # the replica's traces never went through the router
        self._replica_stream(replica, trace_id="direct-9", run_id="abc123")
        out = tmp_path / "merged.json"
        trace.export_chrome_trace([str(router), str(replica)], str(out))
        data = json.loads(out.read_text())
        names = {
            e["args"]["name"] for e in data["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert "replica abc123" in names

    def test_never_completed_requests_exempt_from_resolution(self, tmp_path):
        """A fleet-wide shed leaves proxy_hop spans whose trace id no
        replica ever completed — the gate must not fail a correct
        overload artifact (review fix): only ids with an outcome=ok hop
        must resolve."""
        router, replica = tmp_path / "router.jsonl", tmp_path / "r1.jsonl"
        mono = 5000.0
        shed_spans = [
            _span("proxy_hop", WALL0 + 2.0, 0.01, mono,
                  trace_ids=["t-shed"], replica="127.0.0.1:8081",
                  outcome="shed", attempt=1),
            _span("proxy_hop", WALL0 + 2.02, 0.01, mono,
                  trace_ids=["t-shed"], replica="127.0.0.1:8082",
                  outcome="shed", attempt=2),
        ]
        ok_spans = [
            _span("proxy_hop", WALL0 + 1.0, 0.1, mono,
                  trace_ids=["t1"], replica="127.0.0.1:8081",
                  outcome="ok", attempt=1),
        ]
        _jsonl(router, [
            _envelope("run_started", mono, run_id="router"),
            _envelope("fleet_trace", mono, run_id="router", trace_id="t1",
                      request_id="fl-000001", replica="127.0.0.1:8081",
                      replica_hops=0, status=200, spans=ok_spans),
            _envelope("fleet_trace", mono, run_id="router",
                      trace_id="t-shed", request_id="fl-000002",
                      replica=None, replica_hops=2, status=503,
                      spans=shed_spans),
        ])
        self._replica_stream(replica, trace_id="t1")
        out = tmp_path / "merged.json"
        trace.export_chrome_trace([str(router), str(replica)], str(out))
        res = run_checker("--expect-fleet-trace", out)
        assert res.returncode == 0, res.stderr

    def test_expect_fleet_trace_red_without_replica_stream(self, tmp_path):
        router = tmp_path / "router.jsonl"
        self._router_stream(router, "127.0.0.1:8081")
        out = tmp_path / "router_only.json"
        trace.export_chrome_trace([str(router)], str(out))
        res = run_checker("--expect-fleet-trace", out)
        assert res.returncode == 1
        assert "resolves to no replica-side span tree" in res.stderr

    def test_expect_fleet_trace_red_on_plain_serve_export(self, tmp_path):
        events = tmp_path / "solo.jsonl"
        self._replica_stream(events)
        out = tmp_path / "solo.json"
        trace.export_chrome_trace(str(events), str(out))
        res = run_checker("--expect-fleet-trace", out)
        assert res.returncode == 1
        assert "no proxy_hop span" in res.stderr

    def test_torn_tail_stream_still_merges(self, tmp_path):
        """A SIGKILLed replica's log (torn final line, no run_finished) is
        exactly the post-mortem input — the merge skips the tear."""
        router, replica = tmp_path / "router.jsonl", tmp_path / "r1.jsonl"
        self._router_stream(router, "127.0.0.1:8081")
        self._replica_stream(replica)
        with open(replica, "a") as f:
            f.write('{"event": "serve_trace", "trace_id": "t2", "spa')
        out = tmp_path / "merged.json"
        assert trace.export_chrome_trace(
            [str(router), str(replica)], str(out)
        ) == 2
        res = run_checker("--expect-fleet-trace", out)
        assert res.returncode == 0, res.stderr

    def test_cli_accepts_multiple_streams(self, tmp_path):
        router, replica = tmp_path / "router.jsonl", tmp_path / "r1.jsonl"
        self._router_stream(router, "127.0.0.1:8081")
        self._replica_stream(replica)
        out = tmp_path / "cli_merged.json"
        res = subprocess.run(
            [sys.executable, "-m", "nm03_capstone_project_tpu.obs.trace",
             str(router), str(replica), "-o", str(out)],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert res.returncode == 0, res.stderr
        assert "merged from 2 streams" in res.stdout
        assert json.loads(out.read_text())["metadata"]["processes"] == 2

    def test_fleet_span_vocabulary_pinned(self):
        # the docs-table lockstep contract, fleet section (ISSUE 14)
        assert trace.FLEET_SPAN_NAMES == (
            "route_pick", "proxy_hop", "failover", "canary_probe",
        )
        assert trace.FLEET_TRACE_EVENT == "fleet_trace"


# -- batcher/executor span plumbing (fake executor, no jax) ------------------


class TracingFakeExecutor:
    """Lane-aware, trace-aware executor stand-in (mirrors WarmExecutor)."""

    supports_trace = True

    def __init__(self, buckets=(1, 2, 4), lanes=2, canvas=16, min_dim=4):
        self.cfg = SimpleNamespace(canvas=canvas, min_dim=min_dim)
        self.buckets = tuple(buckets)
        self.lane_count = lanes

    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run_batch(self, pixels, dims, lane=0, trace=None):
        from nm03_capstone_project_tpu.obs.trace import NULL_TRACE

        trace = trace if trace is not None else NULL_TRACE
        with trace.span("device_dispatch", attempt=1):
            mask = (pixels > 0).astype(np.uint8)
        with trace.span("fetch", attempt=1):
            pass
        return mask, np.ones(pixels.shape[0], bool)


class TestBatcherTracePlumbing:
    def _reqs(self, n, hw=8):
        from nm03_capstone_project_tpu.serving.queue import ServeRequest

        return [
            ServeRequest(
                request_id=f"r{i}",
                pixels=np.ones((hw, hw), np.float32),
                dims=(hw, hw),
                trace=trace.TraceContext(f"tr-{i}"),
            )
            for i in range(n)
        ]

    def test_span_tree_and_lane_recorded(self):
        from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
        from nm03_capstone_project_tpu.serving.queue import AdmissionQueue

        ex = TracingFakeExecutor(buckets=(1, 2), lanes=2)
        b = DynamicBatcher(AdmissionQueue(16), ex, max_wait_s=0.0)
        reqs = self._reqs(4)  # 2 chunks of bucket 2 on lanes 0/1
        b.execute(reqs)
        for r in reqs:
            names = [s["name"] for s in r.trace.snapshot()]
            assert names == [
                "queue_wait", "coalesce", "pad_stack", "device_dispatch",
                "fetch",
            ], names
            assert r.lane in (0, 1)
        # chunk spans are SHARED between a chunk's riders, not across chunks
        d0 = [s for s in reqs[0].trace.snapshot()
              if s["name"] == "device_dispatch"][0]
        assert d0["riders"] == 2 and len(d0["trace_ids"]) == 2
        lanes_used = {r.lane for r in reqs}
        assert lanes_used == {0, 1}

    def test_trace_less_requests_still_served(self):
        from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
        from nm03_capstone_project_tpu.serving.queue import (
            AdmissionQueue,
            ServeRequest,
        )

        ex = TracingFakeExecutor(buckets=(1, 2), lanes=2)
        b = DynamicBatcher(AdmissionQueue(16), ex, max_wait_s=0.0)
        reqs = [
            ServeRequest(
                request_id=f"r{i}", pixels=np.ones((8, 8), np.float32),
                dims=(8, 8),
            )
            for i in range(3)
        ]
        b.execute(reqs)
        assert all(r.done.is_set() and r.error is None for r in reqs)

    def test_queue_stamps_pop_time(self):
        from nm03_capstone_project_tpu.serving.queue import AdmissionQueue

        q = AdmissionQueue(4)
        (req,) = self._reqs(1)
        q.put(req)
        batch = q.get_batch(max_batch=1, max_wait_s=0.0)
        assert batch == [req]
        assert req.t_popped >= req.t_admitted > 0


# -- compile-cost accounting -------------------------------------------------


class TestCompileCost:
    def test_hub_times_builds_and_reports_cost(self):
        from nm03_capstone_project_tpu.compilehub import get_hub, programs
        from nm03_capstone_project_tpu.config import PipelineConfig

        # a canvas no other suite uses: guarantees a FRESH spec this test
        # owns, whatever ran before in the process
        cfg = PipelineConfig(canvas=96)
        import jax

        dev = jax.local_devices()[0]
        programs.serve_mask(cfg, bucket=1, device=dev)
        hub = get_hub()
        stats = hub.stats()
        assert stats["total_compile_seconds"] > 0
        per_spec = hub.compile_seconds()
        label = f"serve_mask/1x96x96/lane{dev.id}/pinned"
        assert label in per_spec and per_spec[label] > 0
        (entry,) = [e for e in hub.cost_report() if e["label"] == label]
        assert entry["compile_s"] > 0
        # the XLA analyses are version/backend-dependent: when present
        # they must be positive and coherent, absence is not a failure
        if "flops" in entry:
            assert entry["flops"] > 0
        if "bytes_accessed" in entry and "flops" in entry:
            assert entry["intensity_flops_per_byte"] > 0

    def test_executable_cost_on_aot_compile(self):
        import jax
        import jax.numpy as jnp

        from nm03_capstone_project_tpu.compilehub import (
            aot_compile,
            executable_cost,
            hub_jit,
        )

        fn = hub_jit(lambda x: (x * 2.0).sum())
        compiled, aot_ok = aot_compile(
            fn, jax.ShapeDtypeStruct((8, 8), jnp.float32)
        )
        assert aot_ok
        cost = executable_cost(compiled)
        assert isinstance(cost, dict)
        for v in cost.values():
            assert isinstance(v, float)

    def test_deferred_callable_reports_empty_cost(self):
        from nm03_capstone_project_tpu.compilehub import executable_cost

        assert executable_cost(lambda x: x) == {}


# -- in-process serving ------------------------------------------------------


CFG_CANVAS = CANVAS


@pytest.fixture(scope="module")
def traced_app():
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.serving.server import ServingApp

    app = ServingApp(
        cfg=PipelineConfig(canvas=CFG_CANVAS),
        queue_capacity=32,
        buckets=(1, 2),
        max_wait_s=0.05,
        request_timeout_s=60.0,
        lanes=1,
    )
    app.start()
    yield app
    app.begin_drain(reason="test")
    app.close()


class TestServingTraceE2E:
    def test_trace_id_honored_and_span_tree_emitted(self, traced_app):
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice

        app = traced_app
        img = phantom_slice(CFG_CANVAS, CFG_CANVAS, seed=0)
        results = []
        lock = threading.Lock()

        def one(i):
            p = app.segment(img, render=False, trace_id=f"e2e-{i}")
            with lock:
                results.append(p)

        threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == 6
        for p in results:
            assert p["trace_id"].startswith("e2e-")
            assert p["lane"] == 0
            assert p["queue_wait_s"] >= 0
        traces = [
            r for r in app.obs.events.tail if r["event"] == "serve_trace"
        ]
        by_id = {t["trace_id"]: t for t in traces}
        assert {f"e2e-{i}" for i in range(6)} <= set(by_id)
        names = {s["name"] for t in traces for s in t["spans"]}
        assert {"queue_wait", "coalesce", "pad_stack", "device_dispatch",
                "fetch"} <= names
        # SERVE_SPAN_NAMES is the authoritative vocabulary: a new span
        # name on the request path must be added there (and to the
        # docs/OBSERVABILITY.md schema table) or this trips
        assert names <= set(trace.SERVE_SPAN_NAMES), names

    def test_readyz_carries_compile_cost(self, traced_app):
        st = traced_app.status()
        hub = st["compile_hub"]
        assert hub["total_compile_seconds"] > 0
        assert hub["compile_seconds"], hub
        assert any("serve_mask" in k for k in hub["compile_seconds"])

    def test_cost_gauges_in_snapshot(self, traced_app):
        snap = traced_app.obs.metrics_snapshot()
        by_name = {}
        for m in snap["metrics"]:
            by_name.setdefault(m["name"], []).append(m)
        assert "compile_seconds" in by_name
        for m in by_name["compile_seconds"]:
            assert "spec" in m["labels"] and m["value"] >= 0
        # the gauge must agree with the hub's own per-label map (the
        # /readyz source) — including its sum-on-label-collision rule
        from nm03_capstone_project_tpu.compilehub import get_hub

        hub_map = get_hub().compile_seconds()
        for m in by_name["compile_seconds"]:
            spec = m["labels"]["spec"]
            assert spec in hub_map
            assert m["value"] == pytest.approx(hub_map[spec])

    def test_export_from_event_tail_validates(self, traced_app, tmp_path):
        traces = [
            r for r in traced_app.obs.events.tail
            if r["event"] == "serve_trace"
        ]
        assert traces
        events = trace.chrome_trace_events(traces)
        out = tmp_path / "inproc.trace.json"
        out.write_text(json.dumps({"traceEvents": events}))
        res = run_checker("--expect-trace", out)
        assert res.returncode == 0, res.stderr


class TestDegradationAutoDump:
    def test_hang_degradation_dumps_flight_record(self, tmp_path):
        """The chaos drill: an injected hang trips the dispatch deadline,
        the one-way CPU degradation fires, and the supervisor auto-dumps
        the flight recorder — with the wedged request's trace id inside."""
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice
        from nm03_capstone_project_tpu.resilience import (
            FaultPlan,
            ResilienceConfig,
        )
        from nm03_capstone_project_tpu.serving.server import ServingApp

        plan = FaultPlan.from_spec(json.dumps({
            "seed": 7,
            "faults": [
                {"site": "dispatch", "kind": "hang", "hang_s": 30.0,
                 "count": 1},
            ],
        }))
        flightrec.configure(str(tmp_path))
        app = ServingApp(
            cfg=PipelineConfig(canvas=CFG_CANVAS),
            buckets=(1,),
            max_wait_s=0.0,
            resilience=ResilienceConfig(
                retry_max=1, retry_backoff_s=0.01, dispatch_timeout_s=1.0
            ),
            fault_plan=plan,
            lanes=1,
        )
        app.start()
        try:
            img = phantom_slice(CFG_CANVAS, CFG_CANVAS, seed=1)
            p = app.segment(img, render=False, trace_id="chaos-hang-1")
            assert p["degraded"] is True
            dumps = sorted(tmp_path.glob("nm03_flight_*degraded_deadline*.json"))
            assert dumps, list(tmp_path.iterdir())
            data = json.loads(dumps[0].read_text())
            assert data["schema"] == flightrec.SCHEMA_FLIGHT
            assert "chaos-hang-1" in dumps[0].read_text()
        finally:
            flightrec.configure(None)
            app.begin_drain(reason="test")
            app.close()


# -- loadgen attribution -----------------------------------------------------


class TestLoadgenTrace:
    def test_ids_echoed_and_queue_wait_recorded(self, traced_app):
        from nm03_capstone_project_tpu.serving.loadgen import (
            LoadResult,
            _make_payloads,
            run_load,
        )
        from nm03_capstone_project_tpu.serving.server import make_http_server

        httpd = make_http_server(traced_app, "127.0.0.1", 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            port = httpd.server_address[1]
            payloads = _make_payloads(
                CFG_CANVAS, CFG_CANVAS, n_distinct=2, dicom=False
            )
            result = LoadResult()
            summary = run_load(
                f"http://127.0.0.1:{port}/v1/segment?output=mask",
                payloads, n_requests=8, concurrency=4, rate_rps=0.0,
                timeout_s=60.0, result=result,
            )
            assert summary["requests_ok"] == 8
            assert summary["trace_echo_mismatches"] == 0
            assert summary["queue_wait_ms"]["p95"] >= 0
            assert summary["lanes_observed"].get("0", 0) > 0
            assert len(result.requests) == 8
            for rec in result.requests:
                assert rec["id"].startswith("lg-")
                assert rec["echoed_id"] == rec["id"]
                assert rec["queue_wait_ms"] >= 0 and rec["lane"] == 0
        finally:
            httpd.shutdown()
            httpd.server_close()


# -- subprocess acceptance ---------------------------------------------------


def _wait_port_file(proc, port_file, budget_s=300):
    deadline = time.monotonic() + budget_s
    while not os.path.exists(port_file) and time.monotonic() < deadline:
        if proc.poll() is not None:
            pytest.fail(f"server died: {proc.stdout.read()}")
        time.sleep(0.2)
    assert os.path.exists(port_file), "server never became ready"
    with open(port_file) as f:
        return int(f.read().strip())


class TestAcceptanceMultiLaneTrace:
    @pytest.mark.slow
    def test_four_lane_loadgen_trace_perfetto_loadable(self, tmp_path):
        """The ISSUE 7 acceptance bar: loadgen against ``nm03-serve
        --lanes 4`` yields a Perfetto-loadable export where >=1 coalesced
        batch shows >=2 requests sharing one dispatch span, dispatches
        land on >=2 distinct lanes, every request carries queue-wait/
        coalesce/dispatch/fetch segments, and ``/readyz`` + the metrics
        snapshot carry the compile-cost fields."""
        from nm03_capstone_project_tpu.serving.loadgen import (
            LoadResult,
            _make_payloads,
            run_load,
        )

        port_file = tmp_path / "port"
        events = tmp_path / "events.jsonl"
        metrics = tmp_path / "metrics.json"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1,2", "--lanes", "4",
                "--max-wait-ms", "60", "--heartbeat-s", "0",
                "--log-json", str(events), "--metrics-out", str(metrics),
                "--flight-dir", str(tmp_path),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            port = _wait_port_file(proc, str(port_file))
            base = f"http://127.0.0.1:{port}"
            payloads = _make_payloads(CANVAS, CANVAS, n_distinct=2, dicom=False)
            result = LoadResult()
            summary = run_load(
                base + "/v1/segment?output=mask", payloads,
                n_requests=16, concurrency=16, rate_rps=0.0,
                timeout_s=120.0, result=result,
            )
            assert summary["requests_ok"] == 16, summary
            assert summary["trace_echo_mismatches"] == 0
            assert len(summary["lanes_observed"]) >= 2, summary
            with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
                st = json.loads(r.read())
            assert st["compile_hub"]["total_compile_seconds"] > 0
            assert st["compile_hub"]["compile_seconds"]
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out

        # the flushed stream passes the events gate WITH serve_trace
        # records inside, and the export passes --expect-trace
        trace_out = tmp_path / "serve.trace.json"
        res = subprocess.run(
            [sys.executable, "-m", "nm03_capstone_project_tpu.obs.trace",
             str(events), "-o", str(trace_out)],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        res = run_checker(
            "--events", events, "--metrics", metrics,
            "--expect-trace", trace_out,
            "--expect-histogram", "serving_queue_wait_seconds=16",
        )
        assert res.returncode == 0, res.stderr

        data = json.loads(trace_out.read_text())
        bs = [e for e in data["traceEvents"] if e.get("ph") == "B"]
        dispatches = [e for e in bs if e["name"] == "device_dispatch"]
        assert dispatches
        # >=2 requests share one dispatch span (a coalesced batch)...
        assert any(len(e["args"]["trace_ids"]) >= 2 for e in dispatches), (
            [e["args"] for e in dispatches]
        )
        # ...and dispatches land on >=2 distinct lanes
        lanes = {e["args"].get("lane") for e in dispatches}
        assert len(lanes) >= 2, lanes
        # per-request segment coverage: every loadgen id has the full tree
        spans_by_id: dict = {}
        for e in bs:
            for tid in e["args"]["trace_ids"]:
                spans_by_id.setdefault(tid, set()).add(e["name"])
        lg_ids = [r["id"] for r in result.requests]
        for tid in lg_ids:
            assert {"queue_wait", "coalesce", "device_dispatch",
                    "fetch"} <= spans_by_id.get(tid, set()), tid


class TestSigusr2Drill:
    def test_sigusr2_dumps_inflight_trace_id(self, tmp_path):
        """SIGUSR2 against a live server with a WEDGED in-flight request
        produces an atomic flight-recorder dump naming that request's
        trace id — the wedge post-mortem ISSUE 7 promises."""
        port_file = tmp_path / "port"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            NM03_FAULT_PLAN=json.dumps({
                "seed": 3,
                "faults": [{"site": "dispatch", "kind": "hang",
                            "hang_s": 120.0, "count": 1}],
            }),
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1", "--lanes", "1",
                "--max-wait-ms", "5", "--heartbeat-s", "0",
                "--flight-dir", str(tmp_path),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            port = _wait_port_file(proc, str(port_file))
            base = f"http://127.0.0.1:{port}"
            from nm03_capstone_project_tpu.data.synthetic import phantom_slice

            body = phantom_slice(CANVAS, CANVAS, seed=0).astype("<f4").tobytes()

            def fire():
                req = urllib.request.Request(
                    base + "/v1/segment?output=mask", data=body,
                    headers={
                        "Content-Type": "application/octet-stream",
                        "X-Nm03-Height": str(CANVAS),
                        "X-Nm03-Width": str(CANVAS),
                        "X-Nm03-Request-Id": "wedge-drill-1",
                    },
                    method="POST",
                )
                try:
                    urllib.request.urlopen(req, timeout=10).read()
                except Exception:  # noqa: BLE001 — it is SUPPOSED to wedge
                    pass

            threading.Thread(target=fire, daemon=True).start()
            # wait until the request is admitted and the batcher recorded
            # its queue_wait span into the flight ring, then trigger
            time.sleep(2.0)
            proc.send_signal(signal.SIGUSR2)
            deadline = time.monotonic() + 30
            dump = None
            while time.monotonic() < deadline:
                dumps = sorted(tmp_path.glob("nm03_flight_*sigusr2*.json"))
                if dumps:
                    dump = dumps[0]
                    break
                time.sleep(0.2)
            assert dump is not None, list(tmp_path.iterdir())
            text = dump.read_text()
            data = json.loads(text)  # atomic: parses whole, or not present
            assert data["schema"] == flightrec.SCHEMA_FLIGHT
            assert "wedge-drill-1" in text, text[:2000]
        finally:
            proc.kill()
            proc.communicate(timeout=30)
