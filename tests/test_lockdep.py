"""Runtime lockdep tests (ISSUE 20): the instrumented-lock witness.

Three tiers:

* unit — the wrapper itself: package-frame scoping, RLock reentrancy,
  Condition pass-through, budget accounting, uninstall restoration;
* the ABBA battery — two fixture locks taken in opposite orders on two
  threads (sequentially, so nothing actually deadlocks): the witness must
  record the inversion NAMING BOTH STACKS, and ``explain_witness`` must
  refuse it;
* the live drill — a real mixed slice+volume serving run constructed
  INSIDE the lockdep window, whose witness must gate clean against the
  static may-hold graph (zero inversions, zero cycles, every observed
  edge statically explained or an obs/ leaf), end-to-end through
  ``scripts/check_static.py --lockdep-witness``.

Every test uninstalls in a finally: the factory patch is process-global.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import threading
import time

import pytest

from nm03_capstone_project_tpu.analysis.core import collect_files
from nm03_capstone_project_tpu.analysis.lockorder import (
    build_lock_graph,
    explain_witness,
)
from nm03_capstone_project_tpu.utils import lockdep

REPO = pathlib.Path(__file__).resolve().parents[1]
PKG = "nm03_capstone_project_tpu"
HERE = pathlib.Path(__file__).resolve().parent


@pytest.fixture
def installed():
    """Lockdep installed with this test file's directory instrumented."""
    st = lockdep.install(extra_prefixes=(str(HERE),))
    try:
        yield st
    finally:
        lockdep.uninstall()


def _static_graph():
    files = collect_files(
        [REPO / PKG, REPO / "scripts", REPO / "bench.py"], REPO
    )
    return build_lock_graph(files)


class TestWrapperUnit:
    def test_package_frame_scoping_and_uninstall(self, installed):
        lock = threading.Lock()  # created HERE -> instrumented
        assert type(lock).__name__ == "_InstrumentedLock"
        with lock:
            assert lock.locked()
        assert not lock.locked()
        lockdep.uninstall()
        assert threading.Lock().__class__.__module__ == "_thread"
        # idempotent double-uninstall, and the fixture's finally is a no-op
        assert lockdep.uninstall() is None
        lockdep.install(extra_prefixes=(str(HERE),))  # fixture rebalances

    def test_stdlib_event_and_thread_locks_not_misattributed(self, installed):
        """threading.Event()/Thread() build locks from threading.py frames
        (and numpy builds them from C): none may claim a package site."""
        before = set(installed.snapshot()["sites"] and [])
        ev = threading.Event()
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        ev.set()
        snap = json.dumps(installed.snapshot()["sites"])
        assert "test_lockdep" not in snap, snap

    def test_rlock_reentrancy_records_no_self_edge(self, installed):
        r = threading.RLock()
        assert type(r).__name__ == "_InstrumentedRLock"
        with r:
            with r:
                assert r.locked()
        snap = installed.snapshot()
        assert all(e["src"] != e["dst"] for e in snap["edges"])

    def test_condition_wait_flows_through_tracked_path(self, installed):
        """Condition(instrumented-lock): the wait's release/re-acquire uses
        the wrapper's plain acquire()/release() (no _release_save exposed),
        so the held-set stays balanced across a real wait."""
        inner = threading.Lock()
        cond = threading.Condition(inner)
        done = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                done.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert done == [True]
        # the waiter thread's held stack drained to empty: a fresh acquire
        # records no edge from a stale entry
        probe = threading.Lock()
        with probe:
            pass
        snap = installed.snapshot()
        assert all(e["src"] != e["dst"] for e in snap["edges"])

    def test_hold_budget_flags_slow_hold(self):
        st = lockdep.install(budget_s=0.001, extra_prefixes=(str(HERE),))
        try:
            slow = threading.Lock()
            with slow:
                time.sleep(0.02)
            over = st.snapshot()["over_budget"]
            assert any(o["held_s"] >= 0.01 for o in over)
        finally:
            lockdep.uninstall()

    def test_witness_dump_is_atomic_and_versioned(self, installed, tmp_path):
        lock = threading.Lock()
        with lock:
            pass
        out = lockdep.dump_witness(tmp_path / "w" / "witness.json", installed)
        payload = json.loads(out.read_text())
        assert payload["version"] == 1
        assert not (tmp_path / "w" / "witness.json.tmp").exists()
        assert any(s["acquires"] >= 1 for s in payload["sites"])


class TestAbbaBattery:
    def test_inversion_names_both_stacks(self, installed):
        """The runtime NM421: opposite orders on two threads — caught on
        the second ordering's FIRST acquisition, with the fix's two call
        paths named, not the eventual deadlock's silence."""
        a = threading.Lock()
        b = threading.Lock()

        def forward_path():
            with a:
                with b:
                    pass

        def backward_path():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=forward_path)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=backward_path)
        t2.start()
        t2.join()

        snap = installed.snapshot()
        assert len(snap["inversions"]) == 1
        inv = snap["inversions"][0]
        assert inv["first"] != inv["second"]
        assert any("backward_path" in fr for fr in inv["stack"]), inv
        assert any("forward_path" in fr for fr in inv["prior_stack"]), inv

    def test_explain_witness_refuses_the_abba_witness(self, installed):
        a = threading.Lock()
        b = threading.Lock()

        def nested(first, second):
            with first:
                with second:
                    pass

        for order in ((a, b), (b, a)):
            t = threading.Thread(target=nested, args=order)
            t.start()
            t.join()
        witness = installed.snapshot()
        problems = explain_witness(witness, _static_graph())
        assert any("inversion" in p for p in problems)
        assert any("cycle" in p for p in problems)

    def test_consistent_order_gates_clean(self, installed):
        """Fixture sites outside the package are identity-mapped and only
        cycle-checked: a consistent ABAB discipline passes the gate."""
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
        problems = explain_witness(installed.snapshot(), _static_graph())
        assert problems == []


class TestServingDrill:
    """The acceptance drill: the mixed slice+volume serving test re-run
    under instrumented locks, its witness gated against the static graph."""

    @pytest.fixture(scope="class")
    def drill_witness(self, tmp_path_factory):
        """Construct a 4-lane volume-serving app INSIDE the lockdep window
        (only post-install lock creations are instrumented), drive mixed
        slice+volume traffic over live HTTP, drain, dump the witness."""
        import numpy as np

        st = lockdep.install()
        try:
            from nm03_capstone_project_tpu.config import PipelineConfig
            from nm03_capstone_project_tpu.data.synthetic import phantom_volume
            from nm03_capstone_project_tpu.obs import flightrec
            from nm03_capstone_project_tpu.serving.loadgen import (
                LoadResult,
                _make_payloads,
                run_load,
            )
            from nm03_capstone_project_tpu.serving.server import (
                ServingApp,
                make_http_server,
            )

            flightrec.configure(
                dump_dir=str(tmp_path_factory.mktemp("flight"))
            )
            app = ServingApp(
                cfg=PipelineConfig(canvas=64, min_dim=16),
                buckets=(1, 2),
                lanes=4,
                max_wait_s=0.005,
                volume_serving=True,
                volume_depth_buckets=(8,),
            )
            app.start()
            httpd = make_http_server(app)
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            base = f"http://127.0.0.1:{httpd.server_address[1]}"

            import urllib.request

            vol = np.asarray(
                phantom_volume(n_slices=6, height=64, width=64, seed=9),
                np.float32,
            )
            vol_result = {}

            def volume_worker():
                req = urllib.request.Request(
                    base + "/v1/segment-volume?output=summary",
                    data=vol.astype("<f4").tobytes(),
                    headers={
                        "Content-Type": "application/octet-stream",
                        "X-Nm03-Depth": "6",
                        "X-Nm03-Height": "64",
                        "X-Nm03-Width": "64",
                    },
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=120) as r:
                    vol_result["status"] = r.status

            vt = threading.Thread(target=volume_worker)
            vt.start()
            payloads = _make_payloads(64, 64, n_distinct=2, dicom=False)
            summary = run_load(
                base + "/v1/segment?output=mask", payloads,
                n_requests=8, concurrency=4, rate_rps=0.0,
                timeout_s=120.0, result=LoadResult(),
            )
            vt.join(timeout=120)
            assert vol_result.get("status") == 200
            assert summary["requests_ok"] == 8, summary["statuses"]
            app.begin_drain(reason="lockdep-drill")
            httpd.shutdown()
            httpd.server_close()
            app.close()
            out = tmp_path_factory.mktemp("w") / "lockdep_witness.json"
            lockdep.dump_witness(out, st)
        finally:
            lockdep.uninstall()
        return out

    def test_witness_covers_the_serving_locks(self, drill_witness):
        payload = json.loads(drill_witness.read_text())
        paths = {s["path"] for s in payload["sites"]}
        assert f"{PKG}/serving/batcher.py" in paths
        assert f"{PKG}/serving/executor.py" in paths
        # held-across edges were actually observed (gang -> executor at
        # minimum: every dispatched window holds the gang gate)
        assert payload["edges"], "drill recorded no nesting at all"
        assert payload["inversions"] == []

    def test_witness_gates_clean_against_static_graph(self, drill_witness):
        """THE tentpole acceptance: zero inversions, zero cycles, every
        observed edge explained by the static may-hold graph (or an obs/
        leaf) — 'the lock discipline is sound' as a checked claim."""
        witness = json.loads(drill_witness.read_text())
        problems = explain_witness(witness, _static_graph())
        assert problems == [], "\n".join(problems)

    def test_check_static_gate_subprocess(self, drill_witness):
        """Exit-code aggregation: the --lockdep-witness phase rides the
        same pass/fail contract as parse/lint/ruff."""
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_static.py"),
                "--lockdep-witness",
                str(drill_witness),
            ],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "lockdep: witness OK" in proc.stdout

    def test_check_static_gate_fails_on_inverted_witness(
        self, drill_witness, tmp_path
    ):
        """Break drill for the gate itself: inject a reversed copy of an
        observed edge — the gate must go red, nonzero exit."""
        witness = json.loads(drill_witness.read_text())
        assert witness["edges"]
        e = dict(witness["edges"][0])
        witness["edges"].append(
            {"src": e["dst"], "dst": e["src"], "count": 1,
             "stack": ["fabricated:1 in drill"]}
        )
        bad = tmp_path / "bad_witness.json"
        bad.write_text(json.dumps(witness))
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_static.py"),
                "--lockdep-witness",
                str(bad),
            ],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        assert proc.returncode != 0
        assert "check_static: FAIL" in proc.stdout
