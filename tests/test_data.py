import struct

import numpy as np
import pytest

from nm03_capstone_project_tpu.data import (
    DicomParseError,
    extract_file_number,
    find_patient_dirs,
    load_dicom_files_for_patient,
    phantom_slice,
    read_dicom,
    write_dicom,
    write_synthetic_cohort,
)


def test_dicom_round_trip(tmp_path, rng):
    img = (rng.random((64, 48)) * 4000).astype(np.uint16)
    p = tmp_path / "x.dcm"
    write_dicom(p, img, patient_id="PGBM-0007", instance_number=3)
    s = read_dicom(p)
    assert (s.rows, s.cols) == (64, 48)
    np.testing.assert_array_equal(s.pixels, img.astype(np.float32))
    assert s.meta_str((0x0010, 0x0020)) == "PGBM-0007"
    assert s.meta_str((0x0020, 0x0013)).strip() == "3"


def test_dicom_rescale_applied(tmp_path):
    img = np.full((16, 16), 100, np.uint16)
    p = tmp_path / "r.dcm"
    write_dicom(p, img, rescale_slope=2.0, rescale_intercept=-50.0)
    s = read_dicom(p)
    np.testing.assert_allclose(s.pixels, 150.0)


def test_dicom_implicit_vr(tmp_path):
    """Reader handles implicit VR LE datasets (written by hand here)."""

    def elem(group, el, value):
        return struct.pack("<HHI", group, el, len(value)) + value

    img = np.arange(12, dtype="<u2").reshape(3, 4)
    meta_elems = struct.pack("<HH", 0x0002, 0x0010) + b"UI" + struct.pack(
        "<H", 18
    ) + b"1.2.840.10008.1.2\x00"
    meta = (
        struct.pack("<HH", 0x0002, 0x0000)
        + b"UL"
        + struct.pack("<H", 4)
        + struct.pack("<I", len(meta_elems))
        + meta_elems
    )
    ds = (
        elem(0x0028, 0x0010, struct.pack("<H", 3))
        + elem(0x0028, 0x0011, struct.pack("<H", 4))
        + elem(0x0028, 0x0100, struct.pack("<H", 16))
        + elem(0x7FE0, 0x0010, img.tobytes())
    )
    p = tmp_path / "implicit.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
    s = read_dicom(p)
    np.testing.assert_array_equal(s.pixels, img.astype(np.float32))


def test_dicom_skips_sequences(tmp_path):
    """Undefined-length SQ elements are skipped structurally."""
    img = np.ones((2, 2), dtype="<u2")

    def ex_elem(group, el, vr, value):
        return struct.pack("<HH", group, el) + vr + struct.pack("<H", len(value)) + value

    sq = (
        struct.pack("<HH", 0x0008, 0x1140)
        + b"SQ\x00\x00"
        + struct.pack("<I", 0xFFFFFFFF)
        + struct.pack("<HHI", 0xFFFE, 0xE000, 0xFFFFFFFF)  # item, undefined
        + ex_elem(0x0008, 0x0100, b"SH", b"CODE")
        + struct.pack("<HHI", 0xFFFE, 0xE00D, 0)  # item delimiter
        + struct.pack("<HHI", 0xFFFE, 0xE0DD, 0)  # sequence delimiter
    )
    meta_elems = (
        struct.pack("<HH", 0x0002, 0x0010)
        + b"UI"
        + struct.pack("<H", 20)
        + b"1.2.840.10008.1.2.1\x00"
    )
    meta = (
        struct.pack("<HH", 0x0002, 0x0000)
        + b"UL"
        + struct.pack("<H", 4)
        + struct.pack("<I", len(meta_elems))
        + meta_elems
    )
    ds = (
        sq
        + ex_elem(0x0028, 0x0010, b"US", struct.pack("<H", 2))
        + ex_elem(0x0028, 0x0011, b"US", struct.pack("<H", 2))
        + ex_elem(0x0028, 0x0100, b"US", struct.pack("<H", 16))
        + struct.pack("<HH", 0x7FE0, 0x0010)
        + b"OW\x00\x00"
        + struct.pack("<I", 8)
        + img.tobytes()
    )
    p = tmp_path / "sq.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
    s = read_dicom(p)
    np.testing.assert_array_equal(s.pixels, np.ones((2, 2), np.float32))


def test_dicom_corrupt_rejected(tmp_path):
    p = tmp_path / "bad.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + b"\x01\x02\x03")
    with pytest.raises(DicomParseError):
        read_dicom(p)
    p2 = tmp_path / "trunc.dcm"
    write_dicom(p2, np.ones((32, 32), np.uint16))
    data = p2.read_bytes()
    p2.write_bytes(data[: len(data) // 2])
    with pytest.raises(DicomParseError):
        read_dicom(p2)


def test_extract_file_number():
    assert extract_file_number("1-14.dcm") == 14
    assert extract_file_number("1-1.dcm") == 1
    assert extract_file_number("series2-003.dcm") == 3
    assert extract_file_number("nonumber.dcm") == 1000
    assert extract_file_number("1-14.txt") == 1000


def test_discovery_contract(tmp_path):
    # two patients, one distractor dir, out-of-order filenames
    for pid in ["PGBM-0002", "PGBM-0001", "LICENSE-DIR"]:
        (tmp_path / pid / "seriesA").mkdir(parents=True)
    (tmp_path / "PGBM-0001" / "seriesB").mkdir()
    for name in ["1-10.dcm", "1-2.dcm", "1-1.dcm", "notes.txt", "weird.dcm"]:
        (tmp_path / "PGBM-0001" / "seriesA" / name).write_bytes(b"")
    patients = find_patient_dirs(tmp_path)
    assert patients == ["PGBM-0001", "PGBM-0002"]
    files = load_dicom_files_for_patient(tmp_path, "PGBM-0001")
    assert [f.name for f in files] == ["1-1.dcm", "1-2.dcm", "1-10.dcm", "weird.dcm"]
    # first series dir in sorted order is used
    assert all("seriesA" in str(f) for f in files)


def test_discovery_missing_root(tmp_path):
    with pytest.raises(FileNotFoundError):
        find_patient_dirs(tmp_path / "nope")
    (tmp_path / "PGBM-0009").mkdir()
    with pytest.raises(FileNotFoundError):
        load_dicom_files_for_patient(tmp_path, "PGBM-0009")


def test_synthetic_cohort_end_to_end(tmp_path):
    pids = write_synthetic_cohort(tmp_path, n_patients=2, n_slices=3, height=128, width=128)
    assert find_patient_dirs(tmp_path) == pids
    files = load_dicom_files_for_patient(tmp_path, pids[0])
    assert len(files) == 3
    s = read_dicom(files[0])
    assert (s.rows, s.cols) == (128, 128)
    assert s.meta_str((0x0010, 0x0020)) == pids[0]


def test_phantom_intensity_structure():
    img = phantom_slice(256, 256, seed=0)
    c = img[128, 128]
    assert 1200 <= c <= 2050  # lesion in the region-growing band (raw units)
    assert img[128, 10] == 0.0  # outside the head
