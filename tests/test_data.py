import struct

import numpy as np
import pytest

from nm03_capstone_project_tpu.data import (
    DicomParseError,
    extract_file_number,
    find_patient_dirs,
    load_dicom_files_for_patient,
    phantom_slice,
    read_dicom,
    write_dicom,
    write_synthetic_cohort,
)


def test_dicom_round_trip(tmp_path, rng):
    img = (rng.random((64, 48)) * 4000).astype(np.uint16)
    p = tmp_path / "x.dcm"
    write_dicom(p, img, patient_id="PGBM-0007", instance_number=3)
    s = read_dicom(p)
    assert (s.rows, s.cols) == (64, 48)
    np.testing.assert_array_equal(s.pixels, img.astype(np.float32))
    assert s.meta_str((0x0010, 0x0020)) == "PGBM-0007"
    assert s.meta_str((0x0020, 0x0013)).strip() == "3"


def test_dicom_rescale_applied(tmp_path):
    img = np.full((16, 16), 100, np.uint16)
    p = tmp_path / "r.dcm"
    write_dicom(p, img, rescale_slope=2.0, rescale_intercept=-50.0)
    s = read_dicom(p)
    np.testing.assert_allclose(s.pixels, 150.0)


def test_dicom_implicit_vr(tmp_path):
    """Reader handles implicit VR LE datasets (written by hand here)."""

    def elem(group, el, value):
        return struct.pack("<HHI", group, el, len(value)) + value

    img = np.arange(12, dtype="<u2").reshape(3, 4)
    meta_elems = struct.pack("<HH", 0x0002, 0x0010) + b"UI" + struct.pack(
        "<H", 18
    ) + b"1.2.840.10008.1.2\x00"
    meta = (
        struct.pack("<HH", 0x0002, 0x0000)
        + b"UL"
        + struct.pack("<H", 4)
        + struct.pack("<I", len(meta_elems))
        + meta_elems
    )
    ds = (
        elem(0x0028, 0x0010, struct.pack("<H", 3))
        + elem(0x0028, 0x0011, struct.pack("<H", 4))
        + elem(0x0028, 0x0100, struct.pack("<H", 16))
        + elem(0x7FE0, 0x0010, img.tobytes())
    )
    p = tmp_path / "implicit.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
    s = read_dicom(p)
    np.testing.assert_array_equal(s.pixels, img.astype(np.float32))


def test_dicom_skips_sequences(tmp_path):
    """Undefined-length SQ elements are skipped structurally."""
    img = np.ones((2, 2), dtype="<u2")

    def ex_elem(group, el, vr, value):
        return struct.pack("<HH", group, el) + vr + struct.pack("<H", len(value)) + value

    sq = (
        struct.pack("<HH", 0x0008, 0x1140)
        + b"SQ\x00\x00"
        + struct.pack("<I", 0xFFFFFFFF)
        + struct.pack("<HHI", 0xFFFE, 0xE000, 0xFFFFFFFF)  # item, undefined
        + ex_elem(0x0008, 0x0100, b"SH", b"CODE")
        + struct.pack("<HHI", 0xFFFE, 0xE00D, 0)  # item delimiter
        + struct.pack("<HHI", 0xFFFE, 0xE0DD, 0)  # sequence delimiter
    )
    meta_elems = (
        struct.pack("<HH", 0x0002, 0x0010)
        + b"UI"
        + struct.pack("<H", 20)
        + b"1.2.840.10008.1.2.1\x00"
    )
    meta = (
        struct.pack("<HH", 0x0002, 0x0000)
        + b"UL"
        + struct.pack("<H", 4)
        + struct.pack("<I", len(meta_elems))
        + meta_elems
    )
    ds = (
        sq
        + ex_elem(0x0028, 0x0010, b"US", struct.pack("<H", 2))
        + ex_elem(0x0028, 0x0011, b"US", struct.pack("<H", 2))
        + ex_elem(0x0028, 0x0100, b"US", struct.pack("<H", 16))
        + struct.pack("<HH", 0x7FE0, 0x0010)
        + b"OW\x00\x00"
        + struct.pack("<I", 8)
        + img.tobytes()
    )
    p = tmp_path / "sq.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
    s = read_dicom(p)
    np.testing.assert_array_equal(s.pixels, np.ones((2, 2), np.float32))


def test_dicom_corrupt_rejected(tmp_path):
    p = tmp_path / "bad.dcm"
    p.write_bytes(b"\x00" * 128 + b"DICM" + b"\x01\x02\x03")
    with pytest.raises(DicomParseError):
        read_dicom(p)
    p2 = tmp_path / "trunc.dcm"
    write_dicom(p2, np.ones((32, 32), np.uint16))
    data = p2.read_bytes()
    p2.write_bytes(data[: len(data) // 2])
    with pytest.raises(DicomParseError):
        read_dicom(p2)


class TestImporterEnvelope:
    """Every DicomParseError rejection branch, with actionable messages.

    VERDICT r1 missing #2: FAST's importer (DCMTK) also reads compressed /
    encapsulated transfer syntaxes; dicomlite's envelope is uncompressed
    little endian only (covers the reference's actual T1+C cohort). These
    tests pin the boundary so out-of-envelope files fail loudly with a
    remedy, never silently or confusingly.
    """

    @staticmethod
    def _file_with_ts(tmp_path, ts: str):
        """A valid Part-10 file whose transfer-syntax UID is ``ts``."""
        from nm03_capstone_project_tpu.data.dicomlite import _element

        p = tmp_path / "ts.dcm"
        write_dicom(p, np.ones((8, 8), np.uint16))
        raw = p.read_bytes()
        body = raw[132:]
        # rebuild the meta group around the new UID (lengths differ per UID)
        meta_elems = _element(0x0002, 0x0010, b"UI", ts.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", struct.pack("<I", len(meta_elems)))
            + meta_elems
        )
        # drop the original meta group (group-length element + its payload)
        orig_len = struct.unpack_from("<I", body, 8)[0]
        ds = body[12 + orig_len :]
        p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
        return p

    def test_malformed_big_endian_contained(self, tmp_path):
        # big endian now DECODES (tests/test_gdcm_vectors.py pins it against
        # a GDCM-written file); a little-endian dataset mislabeled with the
        # BE UID must still fail as a clean DicomParseError, never garbage
        p = self._file_with_ts(tmp_path, "1.2.840.10008.1.2.2")
        with pytest.raises(DicomParseError):
            read_dicom(p)

    @pytest.mark.parametrize(
        "ts",
        [
            "1.2.840.10008.1.2.4.100",  # MPEG2 (video — never in envelope)
            "1.2.840.10008.1.2.4.102",  # MPEG-4 AVC (video)
        ],
    )
    def test_compressed_syntax_rejected_with_remedy(self, tmp_path, ts):
        # RLE / JPEG-lossless / baseline-JPEG (TestCompressedTransferSyntaxes),
        # JPEG-LS (tests/test_jpegls.py) and — via the optional GDCM shim —
        # JPEG 2000 (tests/test_gdcm_vectors.py) now decode; everything else
        # still rejects with a remedy
        p = self._file_with_ts(tmp_path, ts)
        with pytest.raises(DicomParseError, match="transcode"):
            read_dicom(p)

    def test_j2k_without_gdcm_rejected_with_remedy(self, tmp_path, monkeypatch):
        import nm03_capstone_project_tpu.data.gdcm_fallback as gf

        monkeypatch.setattr(gf, "available", lambda: False)
        p = self._file_with_ts(tmp_path, "1.2.840.10008.1.2.4.90")
        with pytest.raises(DicomParseError, match="compressed.*transcode"):
            read_dicom(p)

    @pytest.mark.parametrize(
        "ts",
        [
            "1.2.840.10008.1.2.4.50",  # baseline JPEG
            "1.2.840.10008.1.2.4.70",  # JPEG lossless SV1
            "1.2.840.10008.1.2.5",  # RLE
        ],
    )
    def test_decodable_syntax_with_native_pixels_rejected(self, tmp_path, ts):
        # a decodable compressed UID over NATIVE PixelData is malformed and
        # must fail loudly, not silently read the raw bytes
        p = self._file_with_ts(tmp_path, ts)
        with pytest.raises(DicomParseError, match="native/uncompressed"):
            read_dicom(p)

    def test_encapsulated_pixeldata_rejected(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import _element

        # undefined-length PixelData = encapsulated, even under a supported
        # transfer syntax UID (malformed but seen in the wild)
        ds = (
            _element(0x0028, 0x0010, b"US", struct.pack("<H", 2))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", 2))
            + struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
        )
        p = tmp_path / "encap.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + ds)
        with pytest.raises(DicomParseError, match="encapsulated"):
            read_dicom(p)

class TestCompressedTransferSyntaxes:
    """RLE + JPEG-lossless decode bit-exactly; baseline JPEG via PIL.

    VERDICT r2 missing #3 / next-round item 6: the reference importer (DCMTK
    under FAST, FAST_directives.hpp:30) reads compressed archives; these
    round-trips prove the same float32 slice comes out of the compressed and
    uncompressed paths."""

    @pytest.mark.parametrize("ts_name", ["RLE_LOSSLESS", "JPEG_LOSSLESS_SV1"])
    def test_lossless_round_trip_matches_uncompressed(
        self, tmp_path, rng, ts_name
    ):
        from nm03_capstone_project_tpu.data import dicomlite

        img = (rng.random((37, 53)) * 4095).astype(np.uint16)
        img[:10, :10] = 777  # constant block exercises RLE replicate runs
        plain, comp = tmp_path / "p.dcm", tmp_path / "c.dcm"
        write_dicom(plain, img, rescale_slope=2.0, rescale_intercept=-10.0)
        write_dicom(
            comp, img, rescale_slope=2.0, rescale_intercept=-10.0,
            transfer_syntax=getattr(dicomlite, ts_name),
        )
        assert comp.stat().st_size != plain.stat().st_size
        a, b = read_dicom(plain), read_dicom(comp)
        np.testing.assert_array_equal(a.pixels, b.pixels)  # bit-exact
        assert b.pixels.dtype == np.float32

    def test_rle_compresses_runs(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import RLE_LOSSLESS

        img = np.full((64, 64), 1000, np.uint16)  # maximally runnable
        plain, comp = tmp_path / "p.dcm", tmp_path / "c.dcm"
        write_dicom(plain, img)
        write_dicom(comp, img, transfer_syntax=RLE_LOSSLESS)
        assert comp.stat().st_size < plain.stat().st_size / 4
        np.testing.assert_array_equal(read_dicom(comp).pixels, 1000.0)

    def test_baseline_jpeg_decodes_via_pil(self, tmp_path):
        import io
        import struct as st

        from PIL import Image

        from nm03_capstone_project_tpu.data.dicomlite import (
            _element,
            _encapsulate,
            JPEG_BASELINE,
            EXPLICIT_VR_LE,
        )

        # a smooth gradient survives lossy JPEG within a small tolerance
        img = np.tile(np.arange(64, dtype=np.uint8) * 2, (64, 1))
        buf = io.BytesIO()
        Image.fromarray(img, "L").save(buf, "JPEG", quality=95)
        meta_elems = _element(0x0002, 0x0010, b"UI", JPEG_BASELINE.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", st.pack("<I", len(meta_elems)))
            + meta_elems
        )
        ds = (
            _element(0x0028, 0x0010, b"US", st.pack("<H", 64))
            + _element(0x0028, 0x0011, b"US", st.pack("<H", 64))
            + _element(0x0028, 0x0100, b"US", st.pack("<H", 8))
            + _element(0x0028, 0x0103, b"US", st.pack("<H", 0))
            + st.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + st.pack("<I", 0xFFFFFFFF)
            + _encapsulate(buf.getvalue())
        )
        p = tmp_path / "jb.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
        s = read_dicom(p)
        assert s.pixels.shape == (64, 64)
        assert np.abs(s.pixels - img.astype(np.float32)).max() < 8  # lossy

    def test_jpeg_lossless_signed_pixels(self, tmp_path, rng):
        """Signed 16-bit data survives the two's-complement plane recompose."""
        from nm03_capstone_project_tpu.data import codecs

        img = rng.integers(-2000, 2000, (16, 16), dtype=np.int16)
        enc = codecs.jpeg_lossless_encode(img.view(np.uint16))
        dec = codecs.jpeg_lossless_decode(enc).view(np.int16)
        np.testing.assert_array_equal(dec, img)

    def test_rle_fragment_errors(self):
        from nm03_capstone_project_tpu.data import codecs

        with pytest.raises(codecs.CodecError, match="64-byte header"):
            codecs.rle_decode_frame(b"\x00" * 10, 4, 4, 2)
        bad = struct.pack("<16I", 2, 64, 63, *([0] * 13))  # offsets not sorted
        with pytest.raises(codecs.CodecError, match="offsets"):
            codecs.rle_decode_frame(bad + b"\x00" * 8, 4, 4, 2)

    def test_truncated_jpeg_stream_raises(self):
        from nm03_capstone_project_tpu.data import codecs

        img = np.arange(64, dtype=np.uint16).reshape(8, 8)
        enc = codecs.jpeg_lossless_encode(img)
        with pytest.raises(codecs.CodecError):
            codecs.jpeg_lossless_decode(enc[: len(enc) // 2])

    def test_trailing_fill_bytes_rejected_cleanly(self):
        # a stream ending in 0xFF fill bytes used to leave the fill-skip
        # loop at pos+1 == len and raise IndexError past _decode_compressed's
        # CodecError net (ADVICE r4); both decoders must raise CodecError
        from nm03_capstone_project_tpu.data import codecs

        for decode in (codecs.jpeg_lossless_decode, codecs.jpegls_decode):
            with pytest.raises(codecs.CodecError):
                decode(b"\xff\xd8\xff\xff")
            with pytest.raises(codecs.CodecError):
                decode(b"\xff\xd8\xff\xff\xff\xff\xff")

    def test_jpeg_stream_without_sos_rejected(self):
        # SOF3+DHT but no scan header: decoding trailing bytes as entropy
        # data under the default predictor/table would be an acceptance
        # divergence from the native decoder (ADVICE r3)
        from nm03_capstone_project_tpu.data import codecs

        img = np.arange(64, dtype=np.uint16).reshape(8, 8)
        enc = codecs.jpeg_lossless_encode(img)
        i = enc.index(b"\xff\xda")  # strip the SOS segment + scan
        with pytest.raises(codecs.CodecError, match="missing SOS"):
            codecs.jpeg_lossless_decode(enc[:i] + b"\xff\xd9")

    def test_hostile_rle_dimensions_rejected_before_decode(self):
        # a file declaring 65535x65535 must fail the plausibility bound
        # BEFORE rle_decode_frame's replicate pass can expand fragments into
        # a multi-GB host allocation (ADVICE r3; native caps: 32768 / 2^28)
        from nm03_capstone_project_tpu.data.dicomlite import (
            RLE_LOSSLESS,
            DicomParseError,
            _decode_compressed,
        )

        header = struct.pack("<16I", 1, 64, *([0] * 14))
        with pytest.raises(DicomParseError, match="implausible"):
            _decode_compressed(
                RLE_LOSSLESS, [header + b"\x00" * 8], 65535, 65535,
                np.dtype("<u2"),
            )


class TestBasicOffsetTable:
    """ISSUE 3 satellite: a non-empty Basic Offset Table is the
    AUTHORITATIVE frame-boundary source for encapsulated multi-frame
    PixelData; SOI-marker scanning is only the empty-BOT fallback — a
    fragment boundary can coincidentally land on FF D8 bytes (e.g. inside
    a COM segment) and mis-split the stream."""

    @staticmethod
    def _mf_file(tmp_path, name, fragments, bot_entries, nframes=2):
        import struct as st

        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_BASELINE,
            _element,
        )

        item = lambda b: st.pack("<HHI", 0xFFFE, 0xE000, len(b)) + b  # noqa: E731
        bot = (
            st.pack(f"<{len(bot_entries)}I", *bot_entries)
            if bot_entries
            else b""
        )
        pixeldata = (
            st.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + st.pack("<I", 0xFFFFFFFF)
            + item(bot)
            + b"".join(item(f) for f in fragments)
            + st.pack("<HHI", 0xFFFE, 0xE0DD, 0)
        )
        meta_elems = _element(0x0002, 0x0010, b"UI", JPEG_BASELINE.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", st.pack("<I", len(meta_elems)))
            + meta_elems
        )
        ds = (
            _element(0x0028, 0x0008, b"IS", str(nframes).encode())
            + _element(0x0028, 0x0010, b"US", st.pack("<H", 64))
            + _element(0x0028, 0x0011, b"US", st.pack("<H", 64))
            + _element(0x0028, 0x0100, b"US", st.pack("<H", 8))
            + _element(0x0028, 0x0103, b"US", st.pack("<H", 0))
            + pixeldata
        )
        p = tmp_path / name
        p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
        return p

    @staticmethod
    def _frames():
        """Two baseline-JPEG frames; frame 0 carries a COM segment whose
        payload is the two bytes FF D8, and is split into fragments exactly
        at that payload — so the second fragment coincidentally starts with
        an SOI marker."""
        import io
        import struct as st

        from PIL import Image

        def jpeg(arr):
            buf = io.BytesIO()
            Image.fromarray(arr, "L").save(buf, "JPEG", quality=95)
            return buf.getvalue()

        img0 = np.tile(np.arange(64, dtype=np.uint8) * 2, (64, 1))
        img1 = np.ascontiguousarray(img0.T)
        s0, s1 = jpeg(img0), jpeg(img1)
        com = b"\xff\xfe" + st.pack(">H", 4) + b"\xff\xd8"
        s0 = s0[:2] + com + s0[2:]  # SOI, COM(FF D8), rest
        s0 += b"\x00" * (len(s0) % 2)
        s1 += b"\x00" * (len(s1) % 2)
        frag_a, frag_b = s0[:6], s0[6:]  # split INSIDE the COM payload
        assert frag_b[:2] == b"\xff\xd8"  # the coincidental SOI
        return (img0, img1), (frag_a, frag_b, s1)

    def test_bot_authoritative_over_soi_scan(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom_frames

        (img0, img1), (a, b, c) = self._frames()
        # PS3.5 A.4: BOT entries point at each frame's first-fragment item
        # tag, measured from the byte after the BOT item
        bot = [0, 8 + len(a) + 8 + len(b)]
        p = self._mf_file(tmp_path, "bot.dcm", [a, b, c], bot)
        frames = read_dicom_frames(p)
        assert len(frames) == 2
        for fr, img in zip(frames, (img0, img1)):
            assert np.abs(fr.pixels - img.astype(np.float32)).max() < 8  # lossy

    def test_empty_bot_falls_back_to_soi_scan(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom_frames

        (img0, img1), (a, b, c) = self._frames()
        # without the BOT the COM trick mis-splits into 3 "codestreams":
        # the SOI fallback must reject rather than decode garbage ...
        p = self._mf_file(tmp_path, "nobot.dcm", [a, b, c], [])
        with pytest.raises(DicomParseError, match="3 JPEG codestreams"):
            read_dicom_frames(p)
        # ... and still groups correctly when boundaries are honest
        p2 = self._mf_file(tmp_path, "clean.dcm", [a + b, c], [])
        frames = read_dicom_frames(p2)
        assert len(frames) == 2
        for fr, img in zip(frames, (img0, img1)):
            assert np.abs(fr.pixels - img.astype(np.float32)).max() < 8

    def test_bot_entry_count_mismatch_rejected(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom_frames

        _, (a, b, c) = self._frames()
        p = self._mf_file(tmp_path, "short.dcm", [a, b, c], [0])
        with pytest.raises(DicomParseError, match="Basic Offset Table has 1"):
            read_dicom_frames(p)

    def test_bot_off_boundary_offset_rejected(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom_frames

        _, (a, b, c) = self._frames()
        p = self._mf_file(tmp_path, "off.dcm", [a, b, c], [0, 2])
        with pytest.raises(DicomParseError, match="fragment boundary"):
            read_dicom_frames(p)


class TestImporterEnvelopeMinimal:
    @staticmethod
    def _minimal_ds(tmp_path, name, *, rows=True, pixel=True, samples=1,
                    bits=16, pixel_bytes=None):
        from nm03_capstone_project_tpu.data.dicomlite import _element

        parts = []
        if rows:
            parts.append(_element(0x0028, 0x0010, b"US", struct.pack("<H", 4)))
            parts.append(_element(0x0028, 0x0011, b"US", struct.pack("<H", 4)))
        parts.append(_element(0x0028, 0x0002, b"US", struct.pack("<H", samples)))
        parts.append(_element(0x0028, 0x0100, b"US", struct.pack("<H", bits)))
        if pixel:
            payload = (
                pixel_bytes
                if pixel_bytes is not None
                else np.zeros((4, 4), "<u2").tobytes()
            )
            parts.append(_element(0x7FE0, 0x0010, b"OW", payload))
        p = tmp_path / name
        p.write_bytes(b"\x00" * 128 + b"DICM" + b"".join(parts))
        return p

    def test_missing_rows_rejected(self, tmp_path):
        p = self._minimal_ds(tmp_path, "norows.dcm", rows=False)
        with pytest.raises(DicomParseError, match="Rows/Columns/PixelData"):
            read_dicom(p)

    def test_missing_pixeldata_rejected(self, tmp_path):
        p = self._minimal_ds(tmp_path, "nopix.dcm", pixel=False)
        with pytest.raises(DicomParseError, match="Rows/Columns/PixelData"):
            read_dicom(p)

    def test_color_rejected(self, tmp_path):
        p = self._minimal_ds(tmp_path, "rgb.dcm", samples=3)
        with pytest.raises(DicomParseError, match="monochrome.*grayscale"):
            read_dicom(p)

    def test_odd_bits_rejected(self, tmp_path):
        p = self._minimal_ds(tmp_path, "b12.dcm", bits=12)
        with pytest.raises(DicomParseError, match="BitsAllocated=12"):
            read_dicom(p)

    def test_short_pixeldata_rejected(self, tmp_path):
        p = self._minimal_ds(tmp_path, "short.dcm", pixel_bytes=b"\x00" * 10)
        with pytest.raises(DicomParseError, match="10 bytes, expected 32"):
            read_dicom(p)

    def test_element_overrun_rejected(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import _element

        ds = _element(0x0028, 0x0010, b"US", struct.pack("<H", 4))[:-2] + (
            struct.pack("<H", 0xFFF0)  # claimed length >> remaining bytes
        )
        p = tmp_path / "overrun.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + ds + b"\x00" * 4)
        with pytest.raises(DicomParseError):
            read_dicom(p)

    def test_in_envelope_file_still_reads(self, tmp_path):
        # the boundary tests above must not have tightened the happy path
        p = tmp_path / "ok.dcm"
        write_dicom(p, np.arange(64, dtype=np.uint16).reshape(8, 8))
        s = read_dicom(p)
        assert s.pixels.shape == (8, 8)


def test_extract_file_number():
    assert extract_file_number("1-14.dcm") == 14
    assert extract_file_number("1-1.dcm") == 1
    assert extract_file_number("series2-003.dcm") == 3
    assert extract_file_number("nonumber.dcm") == 1000
    assert extract_file_number("1-14.txt") == 1000


def test_discovery_contract(tmp_path):
    # two patients, one distractor dir, out-of-order filenames
    for pid in ["PGBM-0002", "PGBM-0001", "LICENSE-DIR"]:
        (tmp_path / pid / "seriesA").mkdir(parents=True)
    (tmp_path / "PGBM-0001" / "seriesB").mkdir()
    for name in ["1-10.dcm", "1-2.dcm", "1-1.dcm", "notes.txt", "weird.dcm"]:
        (tmp_path / "PGBM-0001" / "seriesA" / name).write_bytes(b"")
    patients = find_patient_dirs(tmp_path)
    assert patients == ["PGBM-0001", "PGBM-0002"]
    files = load_dicom_files_for_patient(tmp_path, "PGBM-0001")
    assert [f.name for f in files] == ["1-1.dcm", "1-2.dcm", "1-10.dcm", "weird.dcm"]
    # first series dir in sorted order is used
    assert all("seriesA" in str(f) for f in files)


def test_discovery_missing_root(tmp_path):
    with pytest.raises(FileNotFoundError):
        find_patient_dirs(tmp_path / "nope")
    (tmp_path / "PGBM-0009").mkdir()
    with pytest.raises(FileNotFoundError):
        load_dicom_files_for_patient(tmp_path, "PGBM-0009")


def test_synthetic_cohort_end_to_end(tmp_path):
    pids = write_synthetic_cohort(tmp_path, n_patients=2, n_slices=3, height=128, width=128)
    assert find_patient_dirs(tmp_path) == pids
    files = load_dicom_files_for_patient(tmp_path, pids[0])
    assert len(files) == 3
    s = read_dicom(files[0])
    assert (s.rows, s.cols) == (128, 128)
    assert s.meta_str((0x0010, 0x0020)) == pids[0]


def test_phantom_intensity_structure():
    img = phantom_slice(256, 256, seed=0)
    c = img[128, 128]
    assert 1200 <= c <= 2050  # lesion in the region-growing band (raw units)
    assert img[128, 10] == 0.0  # outside the head
