"""Chaos suite for the resilience subsystem (ISSUE 3).

Three layers:

* unit — RetryPolicy / Deadline schedules and budgets, FaultPlan parsing,
  matching and seeded determinism, the crash journal's torn-line replay,
  atomic JPEG export;
* driver chaos — both batch drivers under seeded fault plans: failed
  counts equal the plan, no partial/truncated files on disk, injected
  dispatch hangs degrade to the CPU fallback and the cohort still
  finishes (the acceptance test that hangs/crashes on pre-resilience
  main), transient device errors retry;
* crash drill — ``kill -TERM`` mid-run (delivered deterministically by
  the fault plan) followed by ``--resume`` converges to the uninterrupted
  run's exact output set, with no torn files at any point;

plus the telemetry gate: a chaos run's ``--metrics-out`` / ``--log-json``
artifacts validate under scripts/check_telemetry.py including the new
resilience counter/event rules and ``--expect-counter`` assertions.
"""

import hashlib
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from nm03_capstone_project_tpu.cli.runner import CohortProcessor
from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort
from nm03_capstone_project_tpu.obs import RunContext
from nm03_capstone_project_tpu.resilience import (
    Deadline,
    DeadlineExceeded,
    FaultPlan,
    PatientJournal,
    ResilienceConfig,
    RetryPolicy,
    TransientDeviceError,
    is_retryable,
)

CFG = PipelineConfig(canvas=128, render_size=128)
BCFG = BatchConfig(batch_size=3, io_workers=2)
CHECKER = Path(__file__).resolve().parents[1] / "scripts" / "check_telemetry.py"


@pytest.fixture(scope="module")
def cohort(tmp_path_factory):
    root = tmp_path_factory.mktemp("chaos-cohort")
    write_synthetic_cohort(root, n_patients=2, n_slices=4, height=128, width=120)
    return root


def digest_tree(root) -> str:
    h = hashlib.sha256()
    for p in sorted(Path(root).rglob("*.jpg")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def assert_no_torn_files(root):
    """The crash-safety invariant: no stray tmp files, every final-named
    JPEG on disk is structurally complete."""
    from PIL import Image

    assert not list(Path(root).rglob("*.tmp"))
    for p in Path(root).rglob("*.jpg"):
        with Image.open(p) as img:
            img.verify()  # raises on a truncated/torn stream


# -- policies ---------------------------------------------------------------


class TestRetryPolicy:
    def test_schedule_deterministic_and_bounded(self):
        mk = lambda: RetryPolicy(  # noqa: E731
            retry_max=3, backoff_s=0.1, multiplier=2.0, jitter=0.5, seed=7
        )
        a, b = mk(), mk()
        d = [a.delay_s("x", n) for n in (1, 2, 3, 99)]
        assert d == [b.delay_s("x", n) for n in (1, 2, 3, 99)]
        for n, delay in zip((1, 2, 3), d):
            base = min(0.1 * 2 ** (n - 1), a.max_backoff_s)
            assert base * 0.5 <= delay <= base
        assert d[3] <= a.max_backoff_s
        # jitter is per-cause: two causes see different schedules
        assert a.delay_s("x", 1) != a.delay_s("y", 1)

    def test_retries_only_retryable_then_succeeds(self):
        p = RetryPolicy(retry_max=2, backoff_s=0.0)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientDeviceError("blip")
            return "ok"

        assert p.call(flaky, cause="t") == "ok"
        assert len(calls) == 3

        def det():
            calls.append(1)
            raise ValueError("deterministic")

        calls.clear()
        with pytest.raises(ValueError):
            p.call(det, cause="t")
        assert len(calls) == 1  # no retry spent on a deterministic failure

    def test_per_cause_budget_exhausts(self):
        p = RetryPolicy(retry_max=10, backoff_s=0.0, budget_per_cause=2)

        def always():
            raise TransientDeviceError("down")

        with pytest.raises(TransientDeviceError):
            p.call(always, cause="c")
        assert p.spent("c") == 2  # budget, not retry_max, bound the attempts
        # a different cause has its own budget
        with pytest.raises(TransientDeviceError):
            p.call(always, cause="other")
        assert p.spent("other") == 2

    def test_retry_events_flow_through_obs(self):
        ctx = RunContext.create("test")
        p = RetryPolicy(retry_max=1, backoff_s=0.0, obs=ctx)
        calls = []

        def once():
            calls.append(1)
            if len(calls) == 1:
                raise TransientDeviceError("blip")
            return 1

        assert p.call(once, cause="dispatch") == 1
        assert ctx.registry.get(
            "resilience_retries_total", cause="dispatch"
        ).value == 1
        retries = [r for r in ctx.events.tail if r["event"] == "retry"]
        assert retries and retries[0]["attempt"] == 1

    def test_is_retryable_classification(self):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert is_retryable(TransientDeviceError("x"))
        assert is_retryable(XlaRuntimeError("device lost"))
        assert not is_retryable(ValueError("x"))
        assert is_retryable(ValueError("x"), extra=(ValueError,))

    def test_deadline(self):
        d = Deadline.start(0.0)
        assert not d.enabled and d.remaining() == float("inf")
        d = Deadline(budget_s=0.5, started_mono=time.monotonic() - 1.0)
        assert d.expired() and d.remaining() < 0
        with pytest.raises(DeadlineExceeded):
            d.check("dispatch")
        assert not Deadline.start(60.0).expired()

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(retry_max=-1)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=2.0)
        with pytest.raises(ValueError):
            RetryPolicy(multiplier=0.5)


# -- fault plan -------------------------------------------------------------


class TestFaultPlan:
    def test_parse_forms(self, tmp_path):
        spec = {"seed": 5, "faults": [{"site": "decode", "kind": "error"}]}
        for form in (
            spec,
            json.dumps(spec),
            tmp_path / "plan.json",
        ):
            if isinstance(form, Path):
                form.write_text(json.dumps(spec))
                form = str(form)
            plan = FaultPlan.from_spec(form)
            assert plan.seed == 5 and len(plan.rules) == 1
        assert FaultPlan.from_spec(None) is None
        assert FaultPlan.from_env({}) is None
        assert FaultPlan.from_env({"NM03_FAULT_PLAN": json.dumps(spec)}).seed == 5

    def test_validation_rejects_garbage(self):
        with pytest.raises(ValueError, match="site"):
            FaultPlan.from_spec({"faults": [{"site": "nope", "kind": "error"}]})
        with pytest.raises(ValueError, match="invalid for site"):
            FaultPlan.from_spec({"faults": [{"site": "decode", "kind": "hang"}]})
        with pytest.raises(ValueError, match="unknown keys"):
            FaultPlan.from_spec({"faults": [{"site": "decode", "kind": "error", "x": 1}]})
        with pytest.raises(ValueError, match="JSON"):
            FaultPlan.from_spec("{not json")

    def test_selectors_and_count(self):
        plan = FaultPlan.from_spec(
            {"faults": [
                {"site": "export", "kind": "io_error", "stem": "1-02", "count": 1},
            ]}
        )
        assert plan.fire("export", stem="1-01") is None  # selector mismatch
        assert plan.fire("export", stem="1-02") is not None
        assert plan.fire("export", stem="1-02") is None  # count spent
        assert plan.fired_total() == 1
        # patient selector composes with stem
        p2 = FaultPlan.from_spec(
            {"faults": [
                {"site": "decode", "kind": "error", "patient": "P1", "stem": "s"},
            ]}
        )
        assert p2.fire("decode", patient="P2", stem="s") is None
        assert p2.fire("decode", patient="P1", stem="s") is not None

    def test_ordinal_after_is_deterministic_in_order(self):
        plan = FaultPlan.from_spec(
            {"faults": [{"site": "export", "kind": "io_error", "after": 3}]}
        )
        fired = [plan.fire("export", stem=f"s{i}") is not None for i in range(5)]
        assert fired == [False, False, True, True, True]

    def test_rate_keyed_draw_is_schedule_independent(self):
        spec = {"seed": 9, "faults": [{"site": "decode", "kind": "error", "rate": 0.5}]}
        stems = [f"s{i}" for i in range(40)]
        p1, p2 = FaultPlan.from_spec(spec), FaultPlan.from_spec(spec)
        hit1 = {s for s in stems if p1.fire("decode", stem=s)}
        # same plan, reversed check order: the SAME stems are hit
        hit2 = {s for s in reversed(stems) if p2.fire("decode", stem=s)}
        assert hit1 == hit2
        assert 0 < len(hit1) < len(stems)

    def test_site_probes_and_routing(self):
        plan = FaultPlan.from_spec(
            {"faults": [{"site": "decode", "kind": "error", "patient": "P1"}]}
        )
        assert plan.has_site("decode") and not plan.has_site("dispatch")
        assert plan.fire("dispatch", index=0) is None
        # routes_decode is the side-effect-free selector probe
        assert plan.routes_decode(patient="P1", stem="anything")
        assert not plan.routes_decode(patient="P2", stem="anything")
        assert plan.fired_total() == 0  # probing consumed nothing


# -- journal ----------------------------------------------------------------


class TestJournal:
    def test_record_replay(self, tmp_path):
        j = PatientJournal(tmp_path / "P1")
        j.record("1-01", "done")
        j.record("1-02", "failed")
        j.record("1-02", "done")  # last status wins
        j.close()
        assert PatientJournal(tmp_path / "P1").entries() == {
            "1-01": "done", "1-02": "done"
        }

    def test_torn_tail_line_skipped(self, tmp_path):
        j = PatientJournal(tmp_path / "P1")
        j.record("1-01", "done")
        j.close()
        with open(j.path, "a") as f:
            f.write('{"stem": "1-02", "sta')  # crash mid-append
        assert PatientJournal(tmp_path / "P1").entries() == {"1-01": "done"}

    def test_missing_journal_is_empty(self, tmp_path):
        assert PatientJournal(tmp_path / "nope").entries() == {}


# -- atomic export ----------------------------------------------------------


class TestAtomicExport:
    def test_write_is_atomic_and_clean(self, tmp_path):
        from nm03_capstone_project_tpu.render.export import save_jpeg

        img = np.zeros((32, 32), np.uint8)
        save_jpeg(img, tmp_path / "a.jpg")
        assert (tmp_path / "a.jpg").exists()
        assert not list(tmp_path.glob("*.tmp"))

    def test_no_tmp_left_on_encoder_failure(self, tmp_path, monkeypatch):
        from PIL import Image

        from nm03_capstone_project_tpu.render.export import save_jpeg

        def boom(self, *a, **k):
            raise IOError("disk full")

        monkeypatch.setattr(Image.Image, "save", boom)
        with pytest.raises(IOError):
            save_jpeg(np.zeros((8, 8), np.uint8), tmp_path / "b.jpg")
        assert list(tmp_path.iterdir()) == []  # neither b.jpg nor a tmp


# -- driver chaos -----------------------------------------------------------


@pytest.mark.parametrize("mode", ["sequential", "parallel"])
def test_seeded_faults_contained_and_counted(cohort, tmp_path, mode):
    """Failed counts equal the plan; no partial files; counters match."""
    plan = FaultPlan.from_spec({"seed": 3, "faults": [
        {"site": "decode", "kind": "error", "patient": "PGBM-0001", "stem": "1-02"},
        {"site": "decode", "kind": "corrupt", "patient": "PGBM-0002", "stem": "1-01"},
        {"site": "export", "kind": "io_error", "stem": "1-04"},
    ]})
    res = ResilienceConfig(retry_max=2, retry_backoff_s=0.0, fault_plan=plan)
    out = tmp_path / mode
    proc = CohortProcessor(
        cohort, out, cfg=CFG, batch_cfg=BCFG, mode=mode, resilience=res
    )
    summary = proc.process_all_patients()
    d = summary.as_dict()
    assert d["patients_ok"] == 2  # containment holds under chaos
    by_pid = {p.patient_id: p for p in summary.patients}
    assert sorted(by_pid["PGBM-0001"].failed_slices) == ["1-02", "1-04"]
    assert sorted(by_pid["PGBM-0002"].failed_slices) == ["1-01", "1-04"]
    assert d["slices_ok"] == 4 and d["slices_total"] == 8
    # exactly the surviving slices have pairs on disk, none torn
    assert len(list(out.rglob("*.jpg"))) == 2 * 4
    assert_no_torn_files(out)
    # the crash journal recorded every completed slice (per-slice grain in
    # BOTH drivers — the parallel path journals from the export pool)
    j1 = PatientJournal(out / "PGBM-0001").entries()
    assert {s for s, st in j1.items() if st == "done"} == {"1-01", "1-03"}
    # the injected-fault and retry counters match the plan arithmetic:
    # each persistent export fault burns 1 attempt + retry_max retries
    reg = proc.obs.registry
    assert reg.get(
        "resilience_faults_injected_total", site="decode", kind="error"
    ).value == 1
    assert reg.get(
        "resilience_faults_injected_total", site="decode", kind="corrupt"
    ).value == 1
    assert reg.get(
        "resilience_faults_injected_total", site="export", kind="io_error"
    ).value == 2 * (1 + res.retry_max)
    assert reg.get("resilience_retries_total", cause="export").value == (
        2 * res.retry_max
    )
    assert not proc.dispatch.degraded
    assert reg.get("pipeline_degraded_total", cause="deadline") is None


def test_transient_export_fault_healed_by_retry(cohort, tmp_path):
    """A count-limited export fault models a transient disk error: the
    retry heals it and the slice still succeeds."""
    plan = FaultPlan.from_spec({"faults": [
        {"site": "export", "kind": "io_error", "stem": "1-03", "count": 1},
    ]})
    res = ResilienceConfig(retry_max=2, retry_backoff_s=0.0, fault_plan=plan)
    proc = CohortProcessor(
        cohort, tmp_path / "heal", cfg=CFG, mode="sequential", resilience=res
    )
    summary = proc.process_all_patients()
    assert summary.succeeded_slices == 8  # nothing lost
    assert proc.obs.registry.get(
        "resilience_retries_total", cause="export"
    ).value == 1


def test_transient_device_errors_retried_not_fatal(cohort, tmp_path):
    plan = FaultPlan.from_spec({"faults": [
        {"site": "dispatch", "kind": "transient", "count": 2},
    ]})
    res = ResilienceConfig(retry_max=2, retry_backoff_s=0.0, fault_plan=plan)
    proc = CohortProcessor(
        cohort, tmp_path / "t", cfg=CFG, mode="sequential", resilience=res
    )
    summary = proc.process_all_patients()
    assert summary.succeeded_slices == 8
    assert proc.obs.registry.get(
        "resilience_retries_total", cause="dispatch"
    ).value == 2
    assert not proc.dispatch.degraded


@pytest.mark.parametrize("mode", ["sequential", "parallel"])
def test_dispatch_hang_degrades_to_cpu_and_finishes(cohort, tmp_path, mode):
    """ACCEPTANCE: a seeded dispatch hang + --dispatch-timeout-s finishes
    the whole cohort on the CPU fallback, bounded by the deadline, with the
    degradation in metrics + events. On pre-resilience main this test
    cannot pass: the resilience knobs do not exist and an injected
    300-second hang would stall the driver far past the wall bound."""
    plan = FaultPlan.from_spec({"seed": 1, "faults": [
        {"site": "dispatch", "kind": "hang", "index": 0, "hang_s": 300},
    ]})
    res = ResilienceConfig(
        dispatch_timeout_s=1.0, fallback_cpu=True, fault_plan=plan,
        retry_backoff_s=0.0,
    )
    ctx = RunContext.create(mode)
    out = tmp_path / mode
    proc = CohortProcessor(
        cohort, out, cfg=CFG, batch_cfg=BCFG, mode=mode, obs=ctx, resilience=res
    )
    t0 = time.monotonic()
    summary = proc.process_all_patients()
    wall = time.monotonic() - t0
    assert wall < 120  # a 300 s hang NOT abandoned would blow this bound
    assert summary.patients_ok == 2 and summary.succeeded_slices == 8
    assert proc.dispatch.degraded and proc.dispatch.degraded_cause == "deadline"
    assert ctx.registry.get("pipeline_degraded_total", cause="deadline").value == 1
    degraded = [r for r in ctx.events.tail if r["event"] == "degraded"]
    assert len(degraded) == 1  # once per transition, not per batch
    assert degraded[0]["level"] == "WARNING"
    assert ctx.registry.get(
        "resilience_faults_injected_total", site="dispatch", kind="hang"
    ).value == 1
    # the degraded run's outputs are identical to an unfaulted run's
    ref = CohortProcessor(
        cohort, tmp_path / f"ref-{mode}", cfg=CFG, batch_cfg=BCFG, mode=mode
    )
    ref.process_all_patients()
    assert digest_tree(out) == digest_tree(tmp_path / f"ref-{mode}")
    assert_no_torn_files(out)


def test_no_fallback_cpu_fails_fast_instead_of_wedging(cohort, tmp_path):
    plan = FaultPlan.from_spec({"faults": [
        {"site": "dispatch", "kind": "hang", "index": 0, "hang_s": 300},
    ]})
    res = ResilienceConfig(
        dispatch_timeout_s=0.5, fallback_cpu=False, fault_plan=plan,
    )
    proc = CohortProcessor(
        cohort, tmp_path / "ff", cfg=CFG, mode="sequential", resilience=res
    )
    t0 = time.monotonic()
    summary = proc.process_all_patients()
    assert time.monotonic() - t0 < 60
    # the run TERMINATES (every dispatch fails fast after degradation) —
    # never wedges; patients are visited, slices fail
    assert len(summary.patients) == 2
    assert summary.succeeded_slices == 0


def test_fault_plan_cli_flag_and_env(cohort, tmp_path, monkeypatch):
    """--fault-plan and NM03_FAULT_PLAN both reach the processor."""
    from nm03_capstone_project_tpu.cli import common, sequential

    spec = json.dumps({"faults": [{"site": "decode", "kind": "error", "stem": "1-01"}]})
    args = sequential.build_parser().parse_args(
        ["--synthetic", "1", "--fault-plan", spec]
    )
    res = common.resilience_config_from_args(args)
    assert res.fault_plan is not None and res.fault_plan.rules[0].stem == "1-01"
    assert args.fallback_cpu is True
    args2 = sequential.build_parser().parse_args(
        ["--synthetic", "1", "--no-fallback-cpu", "--dispatch-timeout-s", "7",
         "--retry-max", "5"]
    )
    res2 = common.resilience_config_from_args(args2)
    assert (res2.fallback_cpu, res2.dispatch_timeout_s, res2.retry_max) == (
        False, 7.0, 5
    )
    # env activation (no flag): the processor picks it up
    monkeypatch.setenv("NM03_FAULT_PLAN", spec)
    proc = CohortProcessor(cohort, tmp_path / "env", cfg=CFG, mode="sequential")
    assert proc.fault_plan is not None
    monkeypatch.delenv("NM03_FAULT_PLAN")


# -- crash drill ------------------------------------------------------------


def _driver_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def test_sigterm_then_resume_converges(tmp_path):
    """ACCEPTANCE: kill -TERM mid-run (delivered deterministically by the
    fault plan before the 4th slice's export) + --resume yields the same
    final manifest/output set as an uninterrupted run, with no torn files
    at any point, and without re-exporting the journaled slices."""
    cohort = tmp_path / "cohort"
    write_synthetic_cohort(cohort, n_patients=1, n_slices=6, height=128, width=128)
    out = tmp_path / "out"
    plan = json.dumps(
        {"faults": [{"site": "export", "kind": "sigterm", "after": 4}]}
    )
    base_cmd = [
        sys.executable, "-m", "nm03_capstone_project_tpu.cli.sequential",
        "--base-path", str(cohort), "--output", str(out),
        "--canvas", "128", "--render-size", "128", "--device", "cpu",
    ]
    r = subprocess.run(
        base_cmd + ["--fault-plan", plan],
        env=_driver_env(), capture_output=True, text=True, timeout=600,
    )
    assert r.returncode != 0, f"run survived its own SIGTERM: {r.stdout}"

    # crash-safety invariants at the point of death
    assert_no_torn_files(out)
    jpgs = sorted(out.rglob("*.jpg"))
    assert len(jpgs) == 2 * 3  # exactly the 3 journaled slices' pairs
    journal = PatientJournal(out / "PGBM-0001").entries()
    assert len(journal) == 3 and set(journal.values()) == {"done"}
    stamps = {p: p.stat().st_mtime for p in jpgs}

    # resume (drill over: no fault plan) completes the cohort
    r2 = subprocess.run(
        base_cmd + ["--resume"],
        env=_driver_env(), capture_output=True, text=True, timeout=600,
    )
    assert r2.returncode == 0, r2.stderr
    assert_no_torn_files(out)
    for p, mtime in stamps.items():
        assert p.stat().st_mtime == mtime, f"{p.name} was re-exported"

    # converges to the uninterrupted run's exact outputs + manifest
    ref = tmp_path / "ref"
    proc = CohortProcessor(cohort, ref, cfg=CFG, mode="sequential")
    proc.process_all_patients()
    assert digest_tree(out) == digest_tree(ref)
    manifest = json.loads((out / "manifest.json").read_text())
    assert manifest == json.loads((ref / "manifest.json").read_text())
    assert set(manifest["PGBM-0001"].values()) == {"done"}
    assert len(manifest["PGBM-0001"]) == 6


# -- telemetry gate ---------------------------------------------------------


def test_chaos_artifacts_validate_with_expectations(cohort, tmp_path):
    """A chaos run's artifacts pass check_telemetry including the new
    resilience event rules and --expect-counter assertions (satellite 6)."""
    from nm03_capstone_project_tpu.cli import sequential

    plan = json.dumps({"seed": 2, "faults": [
        {"site": "decode", "kind": "error", "stem": "1-02"},
        {"site": "dispatch", "kind": "hang", "index": 0, "hang_s": 300},
    ]})
    m, e = tmp_path / "m.json", tmp_path / "e.jsonl"
    rc = sequential.main([
        "--base-path", str(cohort), "--output", str(tmp_path / "out"),
        "--canvas", "128", "--render-size", "128", "--device", "cpu",
        "--fault-plan", plan, "--dispatch-timeout-s", "1", "--fallback-cpu",
        "--retry-backoff-s", "0",
        "--metrics-out", str(m), "--log-json", str(e),
    ])
    assert rc == 0

    events = [json.loads(line) for line in e.read_text().splitlines()]
    kinds = {r["event"] for r in events}
    assert {"degraded", "fault_injected"} <= kinds
    deg = next(r for r in events if r["event"] == "degraded")
    assert deg["level"] == "WARNING" and deg["cause"] == "deadline"

    check = subprocess.run(
        [sys.executable, str(CHECKER), "--events", str(e), "--metrics", str(m),
         "--expect-patients", "2",
         "--expect-counter", "pipeline_degraded_total=1",
         "--expect-counter", "resilience_faults_injected_total=3"],
        capture_output=True, text=True, timeout=60,
    )
    assert check.returncode == 0, check.stderr

    # the checker REJECTS drifted resilience telemetry
    bad = dict(events[0])
    bad.update(event="degraded", level="INFO", cause="")
    drift = tmp_path / "drift.jsonl"
    drift.write_text(
        "\n".join(json.dumps(r) for r in events[:-1] + [bad, events[-1]]) + "\n"
    )
    # fix seq ordering for the injected record
    records = [json.loads(line) for line in drift.read_text().splitlines()]
    for i, r in enumerate(records):
        r["seq"], r["mono_s"] = i, float(i)
    drift.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    check2 = subprocess.run(
        [sys.executable, str(CHECKER), "--events", str(drift)],
        capture_output=True, text=True, timeout=60,
    )
    assert check2.returncode != 0
    assert "degraded" in check2.stderr
    # and fails an unmet counter expectation
    check3 = subprocess.run(
        [sys.executable, str(CHECKER), "--metrics", str(m),
         "--expect-counter", "pipeline_degraded_total=99"],
        capture_output=True, text=True, timeout=60,
    )
    assert check3.returncode != 0


def test_resume_after_chaos_reprocesses_only_failures(cohort, tmp_path):
    """An injected-fault run + a clean --resume run heals the cohort: the
    failed slices (and only those) are recomputed."""
    out = tmp_path / "heal"
    plan = FaultPlan.from_spec({"faults": [
        {"site": "export", "kind": "io_error", "stem": "1-02"},
    ]})
    res = ResilienceConfig(retry_max=0, fault_plan=plan)
    proc = CohortProcessor(
        cohort, out, cfg=CFG, mode="sequential", resilience=res
    )
    assert proc.process_all_patients().succeeded_slices == 6
    stamps = {p: p.stat().st_mtime for p in out.rglob("*.jpg")}
    proc2 = CohortProcessor(cohort, out, cfg=CFG, mode="sequential", resume=True)
    summary = proc2.process_all_patients()
    assert summary.succeeded_slices == 8
    for p, mtime in stamps.items():
        assert p.stat().st_mtime == mtime  # done slices untouched
    assert len(list(out.rglob("*.jpg"))) == 16
