"""JPEG-LS (ITU-T T.87) decoder conformance + hardening.

The decoder under test is this repo's from-scratch implementation
(data/codecs.py jpegls_decode); the oracle is CharLS, an independent
widely-deployed codec — vendored streams in tests/golden/jpegls/ keep the
conformance leg runnable on machines without libcharls, and the live-CharLS
fuzz leg widens coverage where the library is present (VERDICT r3 items 6-7:
externally-produced vectors, importer breadth to the .80/.81 syntaxes).
"""

import pathlib
import struct
import sys

import numpy as np
import pytest

from nm03_capstone_project_tpu.data.codecs import CodecError, jpegls_decode

sys.path.insert(0, str(pathlib.Path(__file__).parent))
import charls_ref  # noqa: E402

GOLDEN = pathlib.Path(__file__).parent / "golden" / "jpegls"
VECTORS = sorted(p.stem for p in GOLDEN.glob("*.jls"))


class TestVendoredVectors:
    """Bit-exact decode of CharLS-encoded streams (no self-reference)."""

    @pytest.mark.parametrize("name", VECTORS)
    def test_decodes_charls_stream_bit_exact(self, name):
        enc = (GOLDEN / f"{name}.jls").read_bytes()
        want = np.load(GOLDEN / f"{name}.npy")
        got = jpegls_decode(enc)
        np.testing.assert_array_equal(got.astype(np.uint16), want.astype(np.uint16))

    def test_vectors_present(self):
        # six stream shapes: 8/12/16-bit, runs, noise, near-lossless
        assert len(VECTORS) >= 6


@pytest.mark.skipif(not charls_ref.available(), reason="libcharls not present")
class TestLiveCharlsFuzz:
    def test_random_matrix_bit_exact(self):
        rng = np.random.default_rng(11)
        for trial in range(25):
            h, w = int(rng.integers(1, 48)), int(rng.integers(1, 48))
            kind = trial % 4
            if kind == 0:
                img = rng.integers(0, 256, (h, w)).astype(np.uint8)
            elif kind == 1:
                img = (rng.integers(0, 3, (h, w)) * 90).astype(np.uint8)
            elif kind == 2:
                img = rng.integers(0, 1 << 14, (h, w)).astype(np.uint16)
            else:
                img = ((np.add.outer(np.arange(h), np.arange(w)) * 31) % 1024).astype(
                    np.uint16
                )
            near = int(rng.integers(0, 3)) if trial % 5 == 0 else 0
            enc = charls_ref.encode(img, near=near)
            want = charls_ref.decode(enc)
            got = jpegls_decode(enc)
            np.testing.assert_array_equal(
                got.astype(np.uint16), want.astype(np.uint16), err_msg=f"trial {trial}"
            )

    def test_degenerate_shapes(self):
        rng = np.random.default_rng(3)
        for shape in [(1, 1), (1, 31), (31, 1), (2, 2)]:
            img = rng.integers(0, 256, shape).astype(np.uint8)
            enc = charls_ref.encode(img)
            np.testing.assert_array_equal(
                jpegls_decode(enc).astype(np.uint8), charls_ref.decode(enc)
            )


class TestHardening:
    """Corrupt streams raise CodecError — never hang, crash, or mis-shape."""

    @pytest.fixture(scope="class")
    def stream(self):
        return (GOLDEN / "noise16.jls").read_bytes()

    def test_every_truncation_rejected(self, stream):
        for n in range(len(stream)):
            with pytest.raises(CodecError):
                jpegls_decode(stream[:n])

    def test_header_corruption_contained(self, stream):
        rng = np.random.default_rng(9)
        want_shape = np.load(GOLDEN / "noise16.npy").shape
        for _ in range(300):
            m = bytearray(stream)
            i = int(rng.integers(0, len(m)))
            m[i] ^= int(rng.integers(1, 256))
            try:
                out = jpegls_decode(bytes(m))
            except CodecError:
                continue
            # T.87 has no checksum: entropy-body corruption may decode to
            # wrong pixels, but the contract (shape, dtype) must hold
            assert out.shape == want_shape and out.dtype == np.uint16

    def test_missing_sos_rejected(self):
        enc = (GOLDEN / "grad8.jls").read_bytes()
        i = enc.index(b"\xff\xda")
        with pytest.raises(CodecError, match="missing SOS"):
            jpegls_decode(enc[:i] + b"\xff\xd9")

    def test_missing_eoi_rejected(self, stream):
        assert stream.endswith(b"\xff\xd9")
        with pytest.raises(CodecError, match="missing EOI"):
            jpegls_decode(stream[:-2])

    def test_wrong_expected_shape_rejected(self, stream):
        with pytest.raises(CodecError, match="expected"):
            jpegls_decode(stream, expect_shape=(4, 4))

    def test_multi_component_rejected(self):
        # hand-build an SOF55 declaring 3 components
        sof = struct.pack(">BHHB", 8, 4, 4, 3) + b"\x01\x11\x00" * 3
        data = (
            b"\xff\xd8\xff\xf7" + struct.pack(">H", 2 + len(sof)) + sof
            + b"\xff\xd9"
        )
        with pytest.raises(CodecError, match="1 component"):
            jpegls_decode(data)

    def test_fill_bytes_before_markers_accepted(self):
        # optional 0xFF fill bytes before any marker are legal (T.81
        # B.1.1.2, inherited by T.87) — a conformant writer may pad
        enc = (GOLDEN / "grad8.jls").read_bytes()
        want = np.load(GOLDEN / "grad8.npy")
        i = enc.index(b"\xff\xda")
        padded = (
            b"\xff\xd8" + b"\xff" * 3 + enc[2:i] + b"\xff" * 2 + enc[i:-2]
            + b"\xff" + b"\xff\xd9"
        )
        got = jpegls_decode(padded)
        np.testing.assert_array_equal(got.astype(np.uint8), want)

    def test_hostile_reset_rejected(self):
        # RESET outside T.87's [3, max(255, MAXVAL)] must be rejected: an
        # unbounded RESET would let the native mirror's int32 context
        # accumulators overflow before the halving triggers
        enc = (GOLDEN / "grad8.jls").read_bytes()
        i = enc.index(b"\xff\xda")
        lse = b"\xff\xf8" + struct.pack(">HBHHHHH", 13, 1, 255, 3, 7, 21, 0xFFFF)
        with pytest.raises(CodecError, match="RESET"):
            jpegls_decode(enc[:i] + lse + enc[i:])

    def test_interleaved_scan_rejected(self):
        enc = bytearray((GOLDEN / "grad8.jls").read_bytes())
        i = bytes(enc).index(b"\xff\xda")
        # SOS body: len(2) ns(1) [id,table](2) near(1) ilv(1) al(1)
        enc[i + 2 + 2 + 1 + 2 + 1] = 1  # ilv = line-interleaved
        with pytest.raises(CodecError, match="interleave"):
            jpegls_decode(bytes(enc))


class TestImporterIntegration:
    """The .80/.81 transfer syntaxes flow through read_dicom end-to-end."""

    @staticmethod
    def _encapsulated_file(tmp_path, payload, syntax, rows, cols, bits):
        from nm03_capstone_project_tpu.data.dicomlite import _element

        meta_elems = _element(0x0002, 0x0010, b"UI", syntax.encode())
        meta = (
            _element(0x0002, 0x0000, b"UL", struct.pack("<I", len(meta_elems)))
            + meta_elems
        )
        if len(payload) % 2:
            payload += b"\x00"
        frags = (
            struct.pack("<HHI", 0xFFFE, 0xE000, 0)
            + struct.pack("<HHI", 0xFFFE, 0xE000, len(payload))
            + payload
            + struct.pack("<HHI", 0xFFFE, 0xE0DD, 0)
        )
        ds = (
            _element(0x0028, 0x0010, b"US", struct.pack("<H", rows))
            + _element(0x0028, 0x0011, b"US", struct.pack("<H", cols))
            + _element(0x0028, 0x0100, b"US", struct.pack("<H", bits))
            + _element(0x0028, 0x0103, b"US", struct.pack("<H", 0))
            + struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
            + frags
        )
        p = tmp_path / "ls.dcm"
        p.write_bytes(b"\x00" * 128 + b"DICM" + meta + ds)
        return p

    def test_jpegls_lossless_dicom_decodes(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_LOSSLESS,
            read_dicom,
        )

        enc = (GOLDEN / "smooth12.jls").read_bytes()
        want = np.load(GOLDEN / "smooth12.npy")
        p = self._encapsulated_file(
            tmp_path, enc, JPEG_LS_LOSSLESS, *want.shape, bits=16
        )
        s = read_dicom(p)
        np.testing.assert_array_equal(s.pixels.astype(np.uint16), want)

    def test_jpegls_near_dicom_decodes(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_NEAR,
            read_dicom,
        )

        enc = (GOLDEN / "near2_12bit.jls").read_bytes()
        want = np.load(GOLDEN / "near2_12bit.npy")
        p = self._encapsulated_file(tmp_path, enc, JPEG_LS_NEAR, *want.shape, bits=16)
        s = read_dicom(p)
        np.testing.assert_array_equal(s.pixels.astype(np.uint16), want)

    def test_jpegls_8bit_dicom_decodes(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_LOSSLESS,
            read_dicom,
        )

        enc = (GOLDEN / "mask8.jls").read_bytes()
        want = np.load(GOLDEN / "mask8.npy")
        p = self._encapsulated_file(
            tmp_path, enc, JPEG_LS_LOSSLESS, *want.shape, bits=8
        )
        s = read_dicom(p)
        np.testing.assert_array_equal(s.pixels.astype(np.uint8), want)

    def test_shape_mismatch_rejected(self, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_LOSSLESS,
            DicomParseError,
            read_dicom,
        )

        enc = (GOLDEN / "mask8.jls").read_bytes()
        p = self._encapsulated_file(tmp_path, enc, JPEG_LS_LOSSLESS, 8, 8, bits=8)
        with pytest.raises(DicomParseError):
            read_dicom(p)


class TestNativeParity:
    """The C++ decoder (csrc/nm03native.cpp jpegls_decode) agrees bit-exactly
    with both CharLS and the Python decoder through the full native DICOM
    read path — the same acceptance surface, one implementation per layer."""

    @pytest.fixture(scope="class")
    def native(self):
        from nm03_capstone_project_tpu import native

        if not native.available():
            pytest.skip("native layer unavailable")
        return native

    @pytest.mark.parametrize(
        "name,syntax,bits",
        [
            ("smooth12", "1.2.840.10008.1.2.4.80", 16),
            ("near2_12bit", "1.2.840.10008.1.2.4.81", 16),
            ("mask8", "1.2.840.10008.1.2.4.80", 8),
            ("noise16", "1.2.840.10008.1.2.4.80", 16),
            ("grad8", "1.2.840.10008.1.2.4.80", 8),
        ],
    )
    def test_native_decodes_charls_stream_bit_exact(
        self, native, tmp_path, name, syntax, bits
    ):
        enc = (GOLDEN / f"{name}.jls").read_bytes()
        want = np.load(GOLDEN / f"{name}.npy")
        p = TestImporterIntegration._encapsulated_file(
            tmp_path, enc, syntax, *want.shape, bits
        )
        px = native.read_dicom_native(p)
        assert px.shape == want.shape
        np.testing.assert_array_equal(px.astype(np.int64), want.astype(np.int64))

    def test_native_rejects_what_python_rejects(self, native, tmp_path):
        # acceptance agreement on the hardening cases: truncated stream and
        # frame/header dimension disagreement both fail cleanly
        enc = (GOLDEN / "mask8.jls").read_bytes()
        want = np.load(GOLDEN / "mask8.npy")
        p = TestImporterIntegration._encapsulated_file(
            tmp_path, enc[: len(enc) // 2], "1.2.840.10008.1.2.4.80",
            *want.shape, 8
        )
        with pytest.raises(ValueError):
            native.read_dicom_native(p)
        p2 = TestImporterIntegration._encapsulated_file(
            tmp_path, enc, "1.2.840.10008.1.2.4.80", 8, 8, 8
        )
        with pytest.raises(ValueError):
            native.read_dicom_native(p2)

    @pytest.mark.skipif(not charls_ref.available(), reason="libcharls absent")
    def test_native_python_charls_three_way_fuzz(self, native, tmp_path):
        rng = np.random.default_rng(23)
        for trial in range(10):
            h, w = int(rng.integers(2, 40)), int(rng.integers(2, 40))
            if trial % 2:
                img = rng.integers(0, 1 << 12, (h, w)).astype(np.uint16)
                bits = 16
            else:
                img = (rng.integers(0, 5, (h, w)) * 60).astype(np.uint8)
                bits = 8
            near = int(rng.integers(0, 3)) if trial % 3 == 0 else 0
            syntax = (
                "1.2.840.10008.1.2.4.81" if near else "1.2.840.10008.1.2.4.80"
            )
            enc = charls_ref.encode(img, near=near)
            want = charls_ref.decode(enc)
            got_py = jpegls_decode(enc)
            np.testing.assert_array_equal(
                got_py.astype(np.uint16), want.astype(np.uint16)
            )
            d = tmp_path / f"t{trial}"
            d.mkdir()
            p = TestImporterIntegration._encapsulated_file(
                d, enc, syntax, h, w, bits
            )
            got_nat = native.read_dicom_native(p)
            np.testing.assert_array_equal(
                got_nat.astype(np.int64), want.astype(np.int64),
                err_msg=f"trial {trial}",
            )


class TestEncoder:
    """The in-tree JPEG-LS encoder (VERDICT r4 item 8): lossless streams
    that round-trip bit-exactly through the Python decoder, the native
    reader AND CharLS — the writer finally covers the .80 syntax."""

    def test_roundtrip_own_decoder(self, rng):
        from nm03_capstone_project_tpu.data.codecs import jpegls_encode

        for trial in range(30):
            h = int(rng.integers(1, 48))
            w = int(rng.integers(1, 48))
            bits = int(rng.integers(2, 17))
            img = rng.integers(0, 1 << bits, (h, w)).astype(np.uint16)
            enc = jpegls_encode(img)
            np.testing.assert_array_equal(jpegls_decode(enc), img)

    def test_charls_decodes_our_streams(self, rng):
        import charls_ref

        from nm03_capstone_project_tpu.data.codecs import jpegls_encode

        if not charls_ref.available():
            pytest.skip("libcharls unavailable")
        for trial in range(20):
            h = int(rng.integers(1, 40))
            w = int(rng.integers(1, 40))
            kind = trial % 3
            if kind == 0:
                img = rng.integers(0, 4096, (h, w)).astype(np.uint16)
            elif kind == 1:  # run-heavy
                img = (rng.random((h, w)) > 0.7).astype(np.uint16) * 3000
            else:  # constant (trailing-FF + stuffed-pad edge)
                img = np.full((h, w), 57130, np.uint16)
            enc = jpegls_encode(img)
            dec = charls_ref.decode(enc)
            np.testing.assert_array_equal(
                dec.astype(np.uint16).reshape(img.shape), img
            )

    def test_write_dicom_jpegls_roundtrips_both_readers(self, tmp_path, rng):
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_LOSSLESS,
            read_dicom,
            write_dicom,
        )

        img = rng.integers(0, 4000, (33, 47)).astype(np.uint16)
        p = tmp_path / "jls.dcm"
        write_dicom(p, img, transfer_syntax=JPEG_LS_LOSSLESS)
        got = read_dicom(p)
        np.testing.assert_array_equal(got.pixels.astype(np.uint16), img)
        if native.available():
            nat = native.read_dicom_native(p)
            np.testing.assert_array_equal(nat.astype(np.uint16), img)

    def test_trailing_ff_stuffed_pad_accepted_by_both_readers(self, tmp_path):
        # constant high-value images end the entropy segment on an 0xFF
        # data byte; the encoder appends the stuffed 0x00 (CharLS requires
        # it) and both readers must step over it before EOI
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.codecs import jpegls_encode
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_LOSSLESS,
            read_dicom,
            write_dicom,
        )

        img = np.full((19, 49), 57130, np.uint16)
        enc = jpegls_encode(img)
        i = enc.index(b"\xff\xda")
        assert b"\xff\x00\xff\xd9" in enc[i:], "edge case no longer exercised"
        np.testing.assert_array_equal(jpegls_decode(enc), img)
        p = tmp_path / "ff.dcm"
        write_dicom(p, img, transfer_syntax=JPEG_LS_LOSSLESS)
        np.testing.assert_array_equal(
            read_dicom(p).pixels.astype(np.uint16), img
        )
        if native.available():
            np.testing.assert_array_equal(
                native.read_dicom_native(p).astype(np.uint16), img
            )


class TestNearLosslessEncoder:
    """The .81 syntax's encoder half (round 5): near>0 streams whose
    reconstruction is within ±near of the source and BIT-IDENTICAL across
    our decoder, the native reader and CharLS."""

    def test_three_way_reconstruction_identity(self, rng):
        import charls_ref

        from nm03_capstone_project_tpu.data.codecs import jpegls_encode

        if not charls_ref.available():
            pytest.skip("libcharls unavailable")
        for t in range(15):
            h, w = int(rng.integers(1, 40)), int(rng.integers(1, 40))
            near = int(rng.integers(1, 6))
            img = rng.integers(0, 4096, (h, w)).astype(np.uint16)
            enc = jpegls_encode(img, near=near)
            ours = jpegls_decode(enc)
            theirs = charls_ref.decode(enc).astype(np.uint16).reshape(img.shape)
            np.testing.assert_array_equal(ours, theirs)
            assert (
                np.abs(ours.astype(np.int64) - img.astype(np.int64)).max()
                <= near
            )

    def test_write_dicom_near_syntax_round_trips_both_readers(
        self, tmp_path, rng
    ):
        from nm03_capstone_project_tpu import native
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_NEAR,
            read_dicom,
            write_dicom,
        )

        img = rng.integers(0, 4000, (25, 31)).astype(np.uint16)
        p = tmp_path / "near.dcm"
        write_dicom(p, img, transfer_syntax=JPEG_LS_NEAR, jpegls_near=3)
        s = read_dicom(p)
        # lossy storage must declare itself (PS3.3 C.7.6.1.1.5)
        assert s.meta_str((0x0028, 0x2110)) == "01"
        got = s.pixels.astype(np.int64)
        assert np.abs(got - img.astype(np.int64)).max() <= 3
        if native.available():
            nat = native.read_dicom_native(p).astype(np.int64)
            np.testing.assert_array_equal(nat, got)  # identical reconstruction

    def test_near_zero_requires_lossless_syntax(self, tmp_path, rng):
        from nm03_capstone_project_tpu.data.dicomlite import (
            JPEG_LS_NEAR,
            write_dicom,
        )

        img = rng.integers(0, 100, (8, 8)).astype(np.uint16)
        with pytest.raises(ValueError, match="JPEG_LS_LOSSLESS"):
            write_dicom(
                tmp_path / "x.dcm", img,
                transfer_syntax=JPEG_LS_NEAR, jpegls_near=0,
            )
