import jax
import numpy as np
import pytest
import scipy.ndimage as ndi

from nm03_capstone_project_tpu.ops import region_grow, region_grow_jump


def oracle_region_grow(image, seeds, low, high, connectivity=4):
    """Connected-component oracle: pixels in band connected to any seed."""
    band = (image >= low) & (image <= high)
    structure = ndi.generate_binary_structure(2, 1 if connectivity == 4 else 2)
    labels, _ = ndi.label(band, structure=structure)
    seed_labels = np.unique(labels[seeds & band])
    seed_labels = seed_labels[seed_labels != 0]
    return np.isin(labels, seed_labels).astype(np.uint8)


def test_region_grow_simple_blob():
    img = np.zeros((32, 32), np.float32)
    img[8:20, 8:20] = 0.8  # in band
    img[25:30, 25:30] = 0.8  # in band but disconnected from seed
    seeds = np.zeros((32, 32), bool)
    seeds[10, 10] = True
    out = np.asarray(region_grow(img, seeds, 0.74, 0.91)[0])
    expected = oracle_region_grow(img, seeds, 0.74, 0.91)
    np.testing.assert_array_equal(out, expected)
    assert out[26, 26] == 0  # disconnected blob excluded


@pytest.mark.slow
def test_region_grow_matches_oracle_random(rng):
    for trial in range(5):
        img = ndi.gaussian_filter(
            rng.random((48, 48)).astype(np.float32), sigma=2.0
        )
        seeds = np.zeros((48, 48), bool)
        seeds[24, 24] = True
        seeds[10, 35] = True
        lo, hi = 0.45, 0.6
        out = np.asarray(region_grow(img, seeds, lo, hi, block_iters=8)[0])
        expected = oracle_region_grow(img, seeds, lo, hi)
        np.testing.assert_array_equal(out, expected, err_msg=f"trial {trial}")


def test_region_grow_seed_outside_band_is_dead():
    img = np.full((16, 16), 0.5, np.float32)
    seeds = np.zeros((16, 16), bool)
    seeds[8, 8] = True
    out = np.asarray(region_grow(img, seeds, 0.74, 0.91)[0])
    assert out.sum() == 0


def test_region_grow_respects_valid_mask():
    img = np.full((16, 16), 0.8, np.float32)
    seeds = np.zeros((16, 16), bool)
    seeds[4, 4] = True
    valid = np.zeros((16, 16), bool)
    valid[:8, :8] = True
    out = np.asarray(region_grow(img, seeds, 0.74, 0.91, valid=valid)[0])
    assert out[:8, :8].all()
    assert out[8:, :].sum() == 0 and out[:, 8:].sum() == 0


def test_region_grow_snake_path():
    """Long winding path exercises many fixpoint blocks."""
    img = np.zeros((24, 24), np.float32)
    path_rows = list(range(24))
    for i, r in enumerate(path_rows):
        if i % 2 == 0:
            img[r, :23] = 0.8
        else:
            img[r, 1:] = 0.8
    seeds = np.zeros((24, 24), bool)
    seeds[0, 0] = True
    out = np.asarray(region_grow(img, seeds, 0.74, 0.91, block_iters=4)[0])
    expected = oracle_region_grow(img, seeds, 0.74, 0.91)
    np.testing.assert_array_equal(out, expected)
    assert out.sum() == (img > 0).sum()  # whole snake reached


def test_region_grow_vmap_matches_sequential(rng):
    imgs = ndi.gaussian_filter(rng.random((4, 32, 32)), sigma=1.5, axes=(1, 2)).astype(
        np.float32
    )
    seeds = np.zeros((4, 32, 32), bool)
    seeds[:, 16, 16] = True
    f = jax.vmap(lambda i, s: region_grow(i, s, 0.45, 0.6, block_iters=8)[0])
    out = np.asarray(f(imgs, seeds))
    for i in range(4):
        np.testing.assert_array_equal(
            out[i], np.asarray(region_grow(imgs[i], seeds[i], 0.45, 0.6, block_iters=8)[0])
        )


def test_region_grow_8_connectivity():
    img = np.zeros((8, 8), np.float32)
    img[0, 0] = img[1, 1] = img[2, 2] = 0.8  # diagonal chain
    seeds = np.zeros((8, 8), bool)
    seeds[0, 0] = True
    out4 = np.asarray(region_grow(img, seeds, 0.74, 0.91, connectivity=4)[0])
    out8 = np.asarray(region_grow(img, seeds, 0.74, 0.91, connectivity=8)[0])
    assert out4.sum() == 1
    assert out8.sum() == 3


class TestJumpAlgorithm:
    """region_grow_jump: O(log) pointer-jumping schedule, identical sets."""

    @pytest.mark.slow
    def test_matches_scipy_oracle_random(self, rng):
        for trial in range(5):
            img = ndi.gaussian_filter(
                rng.random((48, 48)).astype(np.float32), sigma=2.0
            )
            seeds = np.zeros((48, 48), bool)
            seeds[24, 24] = True
            seeds[10, 35] = True
            out = np.asarray(region_grow_jump(img, seeds, 0.45, 0.6)[0])
            expected = oracle_region_grow(img, seeds, 0.45, 0.6)
            np.testing.assert_array_equal(out, expected, err_msg=f"trial {trial}")

    def test_snake_path_converges_logarithmically(self):
        # the adversarial case for the dilation fixpoint: a 24x24 boustrophedon
        # needs ~500 one-ring steps; the jump schedule must still reach the
        # exact fixpoint (and does so in O(log) rounds by construction)
        img = np.zeros((24, 24), np.float32)
        for i in range(24):
            if i % 2 == 0:
                img[i, :23] = 0.8
            else:
                img[i, 1:] = 0.8
        seeds = np.zeros((24, 24), bool)
        seeds[0, 0] = True
        out = np.asarray(region_grow_jump(img, seeds, 0.74, 0.91)[0])
        np.testing.assert_array_equal(out, oracle_region_grow(img, seeds, 0.74, 0.91))
        assert out.sum() == (img > 0).sum()

    @pytest.mark.parametrize("connectivity", [4, 8])
    @pytest.mark.slow
    def test_bit_identical_to_dilate_path(self, rng, connectivity):
        for trial in range(3):
            img = ndi.gaussian_filter(
                rng.random((40, 40)).astype(np.float32), sigma=1.5
            )
            seeds = np.zeros((40, 40), bool)
            seeds[20, 20] = seeds[5, 30] = seeds[33, 7] = True
            a = np.asarray(
                region_grow(img, seeds, 0.45, 0.6, connectivity=connectivity)[0]
            )
            b = np.asarray(
                region_grow_jump(img, seeds, 0.45, 0.6, connectivity=connectivity)[0]
            )
            np.testing.assert_array_equal(a, b, err_msg=f"trial {trial}")

    def test_valid_mask_and_dead_seed(self):
        img = np.full((16, 16), 0.8, np.float32)
        seeds = np.zeros((16, 16), bool)
        seeds[4, 4] = True
        valid = np.zeros((16, 16), bool)
        valid[:8, :8] = True
        out = np.asarray(region_grow_jump(img, seeds, 0.74, 0.91, valid=valid)[0])
        assert out[:8, :8].all() and out[8:, :].sum() == 0 and out[:, 8:].sum() == 0
        dead = np.asarray(
            region_grow_jump(np.full((16, 16), 0.5, np.float32), seeds, 0.74, 0.91)[0]
        )
        assert dead.sum() == 0

    @pytest.mark.slow
    def test_vmap_matches_per_slice(self, rng):
        imgs = ndi.gaussian_filter(
            rng.random((4, 32, 32)), sigma=1.5, axes=(1, 2)
        ).astype(np.float32)
        seeds = np.zeros((4, 32, 32), bool)
        seeds[:, 16, 16] = True
        f = jax.vmap(lambda i, s: region_grow_jump(i, s, 0.45, 0.6)[0])
        out = np.asarray(f(imgs, seeds))
        for i in range(4):
            np.testing.assert_array_equal(
                out[i], np.asarray(region_grow_jump(imgs[i], seeds[i], 0.45, 0.6)[0])
            )

    def test_rejects_batched_input(self):
        with pytest.raises(ValueError, match="per-slice"):
            region_grow_jump(
                np.zeros((2, 8, 8), np.float32), np.zeros((2, 8, 8), bool), 0.0, 1.0
            )

    def test_jump_plus_pallas_rejected_at_config(self):
        from nm03_capstone_project_tpu.config import PipelineConfig

        with pytest.raises(ValueError, match="mutually exclusive"):
            PipelineConfig(grow_algorithm="jump", use_pallas=True)

    @pytest.mark.slow
    def test_pipeline_with_jump_matches_default(self):
        import dataclasses

        import jax.numpy as jnp

        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice
        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        cfg = PipelineConfig(grow_block_iters=8, grow_max_iters=512)
        cfg_jump = dataclasses.replace(cfg, grow_algorithm="jump")
        x = jnp.asarray(phantom_slice(96, 96, seed=5))
        dims = jnp.asarray([96, 96], np.int32)
        a = process_slice(x, dims, cfg)
        b = process_slice(x, dims, cfg_jump)
        np.testing.assert_array_equal(np.asarray(a["mask"]), np.asarray(b["mask"]))
        assert np.asarray(a["mask"]).sum() > 0


class TestConvergedFlag:
    """VERDICT r4 item 4: a capped (truncated, under-covering) mask must be
    DETECTED — FAST's BFS always completes (main_sequential.cpp:232-243), so
    cap-truncation is a divergence the flag has to surface on every path."""

    def _capped_setup(self):
        # single corner seed in a uniform in-band image: full coverage needs
        # ~2*N growth steps, so a tiny cap is guaranteed to truncate
        img = np.full((64, 64), 0.8, np.float32)
        seeds = np.zeros((64, 64), bool)
        seeds[0, 0] = True
        return img, seeds

    def test_capped_regime_detected(self):
        img, seeds = self._capped_setup()
        mask, conv = region_grow(img, seeds, 0.74, 0.91, block_iters=4, max_iters=8)
        assert not bool(conv)
        assert 0 < np.asarray(mask).sum() < 64 * 64  # truncated, not empty

    def test_full_run_converges(self):
        img, seeds = self._capped_setup()
        mask, conv = region_grow(img, seeds, 0.74, 0.91, block_iters=16, max_iters=512)
        assert bool(conv)
        assert np.asarray(mask).sum() == 64 * 64

    def test_empty_region_converges(self):
        # no seed in band: popcount 0 is stable from the first check
        img = np.full((32, 32), 0.1, np.float32)
        seeds = np.zeros((32, 32), bool)
        seeds[5, 5] = True
        mask, conv = region_grow(img, seeds, 0.74, 0.91)
        assert bool(conv) and np.asarray(mask).sum() == 0

    def test_jump_schedule_converges_where_dilate_caps(self):
        # the O(log) schedule finishes the same image inside its default cap
        img, seeds = self._capped_setup()
        mask, conv = region_grow_jump(img, seeds, 0.74, 0.91)
        assert bool(conv)
        assert np.asarray(mask).sum() == 64 * 64

    def test_vmap_flag_is_per_slice(self):
        # lane 0 caps, lane 1 converges (empty) — the batched flag must
        # distinguish them, not reduce over the batch
        import jax

        img, seeds = self._capped_setup()
        imgs = np.stack([img, np.full((64, 64), 0.1, np.float32)])
        seedss = np.stack([seeds, seeds])
        f = jax.vmap(
            lambda i, s: region_grow(i, s, 0.74, 0.91, block_iters=4, max_iters=8)
        )
        _, conv = f(imgs, seedss)
        conv = np.asarray(conv)
        assert not conv[0] and conv[1]

    def test_pipeline_surfaces_flag(self):
        # the capped single-seed regime reaches process_slice's output dict
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.data.synthetic import phantom_slice
        from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

        x = np.zeros((128, 128), np.float32)
        x[:] = phantom_slice(128, 128, seed=3)
        dims = np.asarray([128, 128], np.int32)
        ok = process_slice(x, dims, PipelineConfig(canvas=128))
        assert bool(np.asarray(ok["grow_converged"]))
        capped = process_slice(
            x, dims,
            PipelineConfig(canvas=128, grow_block_iters=1, grow_max_iters=2),
        )
        # the phantom lesion needs more than 2 one-ring steps
        assert not bool(np.asarray(capped["grow_converged"]))
