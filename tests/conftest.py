"""Test harness configuration.

Tests run on the CPU backend with 8 virtual devices so every sharding /
collective path is exercised without TPU hardware (SURVEY.md section 7 step 8:
"multi-chip via xla_force_host_platform_device_count fake-device testing").
The env vars must be set before jax initializes, which this conftest
guarantees because pytest imports it before any test module.
"""

import os

# Hard-set, not setdefault: the surrounding environment may pin
# JAX_PLATFORMS to a hardware backend, and unit tests must never
# compete for (or hang on) a real accelerator.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A TPU PJRT plugin loaded via sitecustomize may have already called
# jax.config.update("jax_platforms", ...) at interpreter startup, which
# overrides the env var above and would make the first jax.devices()
# dial real hardware (and hang the suite).  Re-pin the live config to
# the CPU backend; this must happen before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
