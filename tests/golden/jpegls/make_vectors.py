"""Regenerate the vendored JPEG-LS conformance vectors.

Each .jls stream is produced by the SYSTEM CharLS library (an independent,
widely-deployed T.87 codec) over a deterministic image; the .npy beside it
is CharLS's own decode of that stream. The suite asserts this repo's
from-scratch decoders (Python + native) reproduce the .npy bit-exactly —
externally-produced streams, not self-round-trips (VERDICT r3 item 6).

Run from the repo root:  python tests/golden/jpegls/make_vectors.py
"""
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
import charls_ref  # noqa: E402

HERE = pathlib.Path(__file__).parent


def main():
    rng = np.random.default_rng(20260731)
    cases = {
        "grad8": (np.tile(np.arange(64, dtype=np.uint8) * 4, (48, 1)), 0),
        "noise8": (rng.integers(0, 256, (33, 41)).astype(np.uint8), 0),
        "mask8": (((rng.random((40, 40)) > 0.85) * 255).astype(np.uint8), 0),
        "smooth12": (
            ((np.add.outer(np.arange(37), np.arange(29)) * 57) % 4096).astype(
                np.uint16
            ),
            0,
        ),
        "noise16": (rng.integers(0, 65536, (21, 27)).astype(np.uint16), 0),
        "near2_12bit": (rng.integers(0, 4096, (25, 25)).astype(np.uint16), 2),
    }
    for name, (img, near) in cases.items():
        enc = charls_ref.encode(img, near=near)
        want = charls_ref.decode(enc)
        (HERE / f"{name}.jls").write_bytes(enc)
        np.save(HERE / f"{name}.npy", want)
        print(f"{name}: {len(enc)} bytes, {want.dtype}{want.shape}, near={near}")


if __name__ == "__main__":
    main()
