// Generate externally-produced DICOM conformance vectors with GDCM.
//
// GDCM is an INDEPENDENT, widely-deployed DICOM implementation (the same
// family of libraries DCMTK-based pipelines interoperate with); the files
// it writes here pin this repo's Python (data/dicomlite.py) and native
// (csrc/nm03native.cpp) readers against streams no code in this repo
// produced (VERDICT r3 item 6). One deterministic 16-bit and one 8-bit
// pattern, written under: Explicit VR LE, Implicit VR LE, RLE Lossless,
// and JPEG Lossless SV1 (1.2.840.10008.1.2.4.70).
//
// Build + run (from the repo root):
//   g++ -O2 -std=c++17 tests/golden/dicom/make_vectors.cpp \
//     -I/usr/include/gdcm-3.0 -lgdcmMSFF -lgdcmDSED -lgdcmCommon \
//     -o /tmp/make_dicom_vectors && /tmp/make_dicom_vectors tests/golden/dicom
#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

#include <gdcmAttribute.h>
#include <gdcmImage.h>
#include <gdcmImageChangeTransferSyntax.h>
#include <gdcmImageWriter.h>
#include <gdcmImageReader.h>
#include <gdcmUIDGenerator.h>

static std::vector<uint8_t> pattern16(unsigned rows, unsigned cols) {
  std::vector<uint8_t> buf(rows * cols * 2);
  for (unsigned y = 0; y < rows; ++y)
    for (unsigned x = 0; x < cols; ++x) {
      // deterministic, full 12-bit range, with flat runs (RLE-friendly)
      uint16_t v = (uint16_t)(((y / 4) * 251 + (x / 4) * 97 + y * x) % 4096);
      buf[2 * (y * cols + x)] = (uint8_t)(v & 0xFF);
      buf[2 * (y * cols + x) + 1] = (uint8_t)(v >> 8);
    }
  return buf;
}

static std::vector<uint8_t> pattern8(unsigned rows, unsigned cols) {
  std::vector<uint8_t> buf(rows * cols);
  for (unsigned y = 0; y < rows; ++y)
    for (unsigned x = 0; x < cols; ++x)
      buf[y * cols + x] = (uint8_t)((y * 7 + (x / 8) * 31) % 256);
  return buf;
}

static bool write_raw(const std::string& path, unsigned rows, unsigned cols,
                      int bits, const std::vector<uint8_t>& pix,
                      gdcm::TransferSyntax::TSType ts,
                      bool monochrome1 = false) {
  gdcm::ImageWriter w;
  gdcm::Image& img = w.GetImage();
  img.SetNumberOfDimensions(2);
  unsigned int dims[2] = {cols, rows};
  img.SetDimensions(dims);
  gdcm::PixelFormat pf(bits == 16 ? gdcm::PixelFormat::UINT16
                                  : gdcm::PixelFormat::UINT8);
  img.SetPixelFormat(pf);
  img.SetPhotometricInterpretation(
      monochrome1 ? gdcm::PhotometricInterpretation::MONOCHROME1
                  : gdcm::PhotometricInterpretation::MONOCHROME2);
  img.SetTransferSyntax(gdcm::TransferSyntax(ts));
  gdcm::DataElement pixeldata(gdcm::Tag(0x7FE0, 0x0010));
  pixeldata.SetByteValue((const char*)pix.data(), (uint32_t)pix.size());
  img.SetDataElement(pixeldata);
  w.SetFileName(path.c_str());
  return w.Write();
}

static bool transcode(const std::string& src, const std::string& dst,
                      gdcm::TransferSyntax::TSType ts) {
  gdcm::ImageReader r;
  r.SetFileName(src.c_str());
  if (!r.Read()) return false;
  gdcm::ImageChangeTransferSyntax change;
  change.SetTransferSyntax(gdcm::TransferSyntax(ts));
  change.SetInput(r.GetImage());
  if (!change.Change()) return false;
  gdcm::ImageWriter w;
  w.SetFileName(dst.c_str());
  w.SetFile(r.GetFile());
  w.SetImage(change.GetOutput());
  return w.Write();
}

int main(int argc, char** argv) {
  std::string out = argc > 1 ? argv[1] : ".";
  const unsigned R = 60, C = 48;  // non-square; GDCM's RLE encoder asserts on odd widths
  auto p16 = pattern16(R, C);
  auto p8 = pattern8(R, C);
  struct Job { const char* name; int bits; gdcm::TransferSyntax::TSType ts; };
  bool ok = true;
  ok &= write_raw(out + "/gdcm16_explicit.dcm", R, C, 16,
                  p16, gdcm::TransferSyntax::ExplicitVRLittleEndian);
  ok &= write_raw(out + "/gdcm16_implicit.dcm", R, C, 16,
                  p16, gdcm::TransferSyntax::ImplicitVRLittleEndian);
  ok &= write_raw(out + "/gdcm8_explicit.dcm", R, C, 8,
                  p8, gdcm::TransferSyntax::ExplicitVRLittleEndian);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_rle.dcm",
                  gdcm::TransferSyntax::RLELossless);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_jpegll.dcm",
                  gdcm::TransferSyntax::JPEGLosslessProcess14_1);
  ok &= transcode(out + "/gdcm8_explicit.dcm", out + "/gdcm8_rle.dcm",
                  gdcm::TransferSyntax::RLELossless);
  ok &= transcode(out + "/gdcm8_explicit.dcm", out + "/gdcm8_jpegll.dcm",
                  gdcm::TransferSyntax::JPEGLosslessProcess14_1);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_bigendian.dcm",
                  gdcm::TransferSyntax::ExplicitVRBigEndian);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_j2k.dcm",
                  gdcm::TransferSyntax::JPEG2000Lossless);
  ok &= transcode(out + "/gdcm8_explicit.dcm", out + "/gdcm8_j2k.dcm",
                  gdcm::TransferSyntax::JPEG2000Lossless);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_deflated.dcm",
                  gdcm::TransferSyntax::DeflatedExplicitVRLittleEndian);
  ok &= write_raw(out + "/gdcm16_mono1.dcm", R, C, 16, p16,
                  gdcm::TransferSyntax::ExplicitVRLittleEndian,
                  /*monochrome1=*/true);
  std::printf(ok ? "all vectors written to %s\n" : "FAILED (partial in %s)\n",
              out.c_str());
  return ok ? 0 : 1;
}
