// Generate externally-produced DICOM conformance vectors with GDCM.
//
// GDCM is an INDEPENDENT, widely-deployed DICOM implementation (the same
// family of libraries DCMTK-based pipelines interoperate with); the files
// it writes here pin this repo's Python (data/dicomlite.py) and native
// (csrc/nm03native.cpp) readers against streams no code in this repo
// produced (VERDICT r3 item 6). One deterministic 16-bit and one 8-bit
// pattern, written under: Explicit VR LE, Implicit VR LE, RLE Lossless,
// and JPEG Lossless SV1 (1.2.840.10008.1.2.4.70).
//
// Build + run (from the repo root):
//   g++ -O2 -std=c++17 tests/golden/dicom/make_vectors.cpp \
//     -I/usr/include/gdcm-3.0 -lgdcmMSFF -lgdcmDSED -lgdcmCommon \
//     -o /tmp/make_dicom_vectors && /tmp/make_dicom_vectors tests/golden/dicom
#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

#include <gdcmAttribute.h>
#include <gdcmImage.h>
#include <gdcmImageChangeTransferSyntax.h>
#include <gdcmImageWriter.h>
#include <gdcmImageReader.h>
#include <gdcmUIDGenerator.h>

static std::vector<uint8_t> pattern16(unsigned rows, unsigned cols) {
  std::vector<uint8_t> buf(rows * cols * 2);
  for (unsigned y = 0; y < rows; ++y)
    for (unsigned x = 0; x < cols; ++x) {
      // deterministic, full 12-bit range, with flat runs (RLE-friendly)
      uint16_t v = (uint16_t)(((y / 4) * 251 + (x / 4) * 97 + y * x) % 4096);
      buf[2 * (y * cols + x)] = (uint8_t)(v & 0xFF);
      buf[2 * (y * cols + x) + 1] = (uint8_t)(v >> 8);
    }
  return buf;
}

static std::vector<uint8_t> pattern8(unsigned rows, unsigned cols) {
  std::vector<uint8_t> buf(rows * cols);
  for (unsigned y = 0; y < rows; ++y)
    for (unsigned x = 0; x < cols; ++x)
      buf[y * cols + x] = (uint8_t)((y * 7 + (x / 8) * 31) % 256);
  return buf;
}

static bool write_raw(const std::string& path, unsigned rows, unsigned cols,
                      int bits, const std::vector<uint8_t>& pix,
                      gdcm::TransferSyntax::TSType ts,
                      bool monochrome1 = false) {
  gdcm::ImageWriter w;
  gdcm::Image& img = w.GetImage();
  img.SetNumberOfDimensions(2);
  unsigned int dims[2] = {cols, rows};
  img.SetDimensions(dims);
  gdcm::PixelFormat pf(bits == 16 ? gdcm::PixelFormat::UINT16
                                  : gdcm::PixelFormat::UINT8);
  img.SetPixelFormat(pf);
  img.SetPhotometricInterpretation(
      monochrome1 ? gdcm::PhotometricInterpretation::MONOCHROME1
                  : gdcm::PhotometricInterpretation::MONOCHROME2);
  img.SetTransferSyntax(gdcm::TransferSyntax(ts));
  gdcm::DataElement pixeldata(gdcm::Tag(0x7FE0, 0x0010));
  pixeldata.SetByteValue((const char*)pix.data(), (uint32_t)pix.size());
  img.SetDataElement(pixeldata);
  w.SetFileName(path.c_str());
  return w.Write();
}

static bool transcode(const std::string& src, const std::string& dst,
                      gdcm::TransferSyntax::TSType ts) {
  gdcm::ImageReader r;
  r.SetFileName(src.c_str());
  if (!r.Read()) return false;
  gdcm::ImageChangeTransferSyntax change;
  change.SetTransferSyntax(gdcm::TransferSyntax(ts));
  change.SetInput(r.GetImage());
  if (!change.Change()) return false;
  gdcm::ImageWriter w;
  w.SetFileName(dst.c_str());
  w.SetFile(r.GetFile());
  w.SetImage(change.GetOutput());
  return w.Write();
}

static bool write_multiframe(const std::string& path, unsigned rows,
                             unsigned cols, unsigned frames,
                             gdcm::TransferSyntax::TSType ts) {
  gdcm::ImageWriter w;
  gdcm::Image& img = w.GetImage();
  img.SetNumberOfDimensions(3);
  unsigned int dims[3] = {cols, rows, frames};
  img.SetDimensions(dims);
  img.SetPixelFormat(gdcm::PixelFormat(gdcm::PixelFormat::UINT16));
  img.SetPhotometricInterpretation(
      gdcm::PhotometricInterpretation::MONOCHROME2);
  img.SetTransferSyntax(
      gdcm::TransferSyntax(gdcm::TransferSyntax::ExplicitVRLittleEndian));
  std::vector<uint8_t> pix;
  for (unsigned f = 0; f < frames; ++f) {
    auto p = pattern16(rows, cols);
    for (size_t i = 0; i < p.size(); i += 2) {
      // distinct per-frame content: frame index folds into the low byte
      p[i] = (uint8_t)(p[i] ^ (f * 31));
    }
    pix.insert(pix.end(), p.begin(), p.end());
  }
  gdcm::DataElement pixeldata(gdcm::Tag(0x7FE0, 0x0010));
  pixeldata.SetByteValue((const char*)pix.data(), (uint32_t)pix.size());
  img.SetDataElement(pixeldata);
  if (ts == gdcm::TransferSyntax::ExplicitVRLittleEndian) {
    w.SetFileName(path.c_str());
    return w.Write();
  }
  // write raw to temp, transcode to the requested encapsulated syntax
  std::string tmp = path + ".raw.dcm";
  w.SetFileName(tmp.c_str());
  if (!w.Write()) return false;
  bool ok = transcode(tmp, path, ts);
  std::remove(tmp.c_str());
  return ok;
}

// a vector carrying real-archive presentation tags the importer must NOT
// trip over: WindowCenter/Width (multi-valued DS) and a stray
// PlanarConfiguration on a monochrome image
static bool write_windowed(const std::string& path, unsigned rows,
                           unsigned cols) {
  gdcm::ImageWriter w;
  gdcm::Image& img = w.GetImage();
  img.SetNumberOfDimensions(2);
  unsigned int dims[2] = {cols, rows};
  img.SetDimensions(dims);
  img.SetPixelFormat(gdcm::PixelFormat(gdcm::PixelFormat::UINT16));
  img.SetPhotometricInterpretation(
      gdcm::PhotometricInterpretation::MONOCHROME2);
  img.SetTransferSyntax(
      gdcm::TransferSyntax(gdcm::TransferSyntax::ExplicitVRLittleEndian));
  auto pix = pattern16(rows, cols);
  gdcm::DataElement pixeldata(gdcm::Tag(0x7FE0, 0x0010));
  pixeldata.SetByteValue((const char*)pix.data(), (uint32_t)pix.size());
  img.SetDataElement(pixeldata);
  gdcm::DataSet& ds = w.GetFile().GetDataSet();
  gdcm::Attribute<0x0028, 0x1050> wc;
  const double wcv[2] = {1024.0, 2048.0};
  wc.SetValues(wcv, 2);
  gdcm::Attribute<0x0028, 0x1051> ww;
  const double wwv[2] = {512.0, 1024.0};
  ww.SetValues(wwv, 2);
  gdcm::Attribute<0x0028, 0x0006> planar;
  planar.SetValue(0);
  ds.Replace(wc.GetAsDataElement());
  ds.Replace(ww.GetAsDataElement());
  ds.Replace(planar.GetAsDataElement());
  w.SetFileName(path.c_str());
  return w.Write();
}

int main(int argc, char** argv) {
  std::string out = argc > 1 ? argv[1] : ".";
  const unsigned R = 60, C = 48;  // non-square; GDCM's RLE encoder asserts on odd widths
  auto p16 = pattern16(R, C);
  auto p8 = pattern8(R, C);
  struct Job { const char* name; int bits; gdcm::TransferSyntax::TSType ts; };
  bool ok = true;
  ok &= write_raw(out + "/gdcm16_explicit.dcm", R, C, 16,
                  p16, gdcm::TransferSyntax::ExplicitVRLittleEndian);
  ok &= write_raw(out + "/gdcm16_implicit.dcm", R, C, 16,
                  p16, gdcm::TransferSyntax::ImplicitVRLittleEndian);
  ok &= write_raw(out + "/gdcm8_explicit.dcm", R, C, 8,
                  p8, gdcm::TransferSyntax::ExplicitVRLittleEndian);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_rle.dcm",
                  gdcm::TransferSyntax::RLELossless);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_jpegll.dcm",
                  gdcm::TransferSyntax::JPEGLosslessProcess14_1);
  ok &= transcode(out + "/gdcm8_explicit.dcm", out + "/gdcm8_rle.dcm",
                  gdcm::TransferSyntax::RLELossless);
  ok &= transcode(out + "/gdcm8_explicit.dcm", out + "/gdcm8_jpegll.dcm",
                  gdcm::TransferSyntax::JPEGLosslessProcess14_1);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_bigendian.dcm",
                  gdcm::TransferSyntax::ExplicitVRBigEndian);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_j2k.dcm",
                  gdcm::TransferSyntax::JPEG2000Lossless);
  ok &= transcode(out + "/gdcm8_explicit.dcm", out + "/gdcm8_j2k.dcm",
                  gdcm::TransferSyntax::JPEG2000Lossless);
  ok &= transcode(out + "/gdcm16_explicit.dcm", out + "/gdcm16_deflated.dcm",
                  gdcm::TransferSyntax::DeflatedExplicitVRLittleEndian);
  ok &= write_raw(out + "/gdcm16_mono1.dcm", R, C, 16, p16,
                  gdcm::TransferSyntax::ExplicitVRLittleEndian,
                  /*monochrome1=*/true);
  // real-archive shapes (round 5): odd dims, presentation tags, multi-frame
  const unsigned OR_ = 59, OC = 47;  // both odd (RLE excluded: GDCM's
                                     // encoder asserts on odd widths)
  auto podd = pattern16(OR_, OC);
  ok &= write_raw(out + "/gdcm16_odd.dcm", OR_, OC, 16, podd,
                  gdcm::TransferSyntax::ExplicitVRLittleEndian);
  ok &= transcode(out + "/gdcm16_odd.dcm", out + "/gdcm16_odd_jpegll.dcm",
                  gdcm::TransferSyntax::JPEGLosslessProcess14_1);
  ok &= write_windowed(out + "/gdcm16_window.dcm", R, C);
  ok &= write_multiframe(out + "/gdcm16_multiframe.dcm", 32, 28, 3,
                         gdcm::TransferSyntax::ExplicitVRLittleEndian);
  ok &= write_multiframe(out + "/gdcm16_multiframe_rle.dcm", 32, 28, 3,
                         gdcm::TransferSyntax::RLELossless);
  std::printf(ok ? "all vectors written to %s\n" : "FAILED (partial in %s)\n",
              out.c_str());
  return ok ? 0 : 1;
}
