"""Regenerate the committed golden stage renders.

Run DELIBERATELY (never from CI) when the renderer contract changes on
purpose:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python tests/golden/make_goldens.py

Each .npz holds the 5 stage renders of one fixed phantom slice through the
test-pipeline contract (src/test/test_pipeline.cpp:162-179), produced by
:func:`nm03_capstone_project_tpu.cli.test_pipeline.stage_renders` — the
exact function the CLI exports through. tests/test_golden.py asserts today's
pixels still match.
"""

import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))  # repo root

GOLDEN_DIR = pathlib.Path(__file__).parent
SEEDS = (17, 3, 11)  # 17 = the CLI's default phantom (test_pipeline.py)
CANVAS = 256


def compute_renders(seed: int) -> dict:
    from nm03_capstone_project_tpu.cli.test_pipeline import stage_renders
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    cfg = PipelineConfig(canvas=CANVAS)
    # lesion size keyed to the seed so each golden pins a DIFFERENT mask
    # geometry (identical masks would triple-count one case)
    radius = {17: 0.10, 3: 0.13, 11: 0.16}.get(seed, 0.12)
    pixels = phantom_slice(CANVAS, CANVAS, seed=seed, lesion_radius=radius)
    dims = np.asarray([CANVAS, CANVAS], np.int32)
    return stage_renders(pixels.astype(np.float32), dims, cfg)


def main() -> int:
    for seed in SEEDS:
        renders = compute_renders(seed)
        out = GOLDEN_DIR / f"stage_renders_seed{seed}.npz"
        np.savez_compressed(out, **renders)
        sizes = {k: int(v.sum()) for k, v in renders.items()}
        print(f"wrote {out.name}: checksums {sizes}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
