"""Volumetric CLI driver end-to-end over a synthetic cohort.

Covers: per-patient 3D segmentation with the JPEG-pair export contract, the
z-sharded path on the 8-virtual-device mesh, MetaImage mask export, and
per-patient failure containment.
"""

import json
import pathlib

import jax
import pytest

from nm03_capstone_project_tpu.cli import volume as volume_cli
from nm03_capstone_project_tpu.data.imageio import read_metaimage


def _run(tmp_path, *extra):
    out = tmp_path / "out-volume"
    argv = [
        "--synthetic", "2",
        "--synthetic-slices", "4",
        "--output", str(out),
        "--results-json", str(out / "res.json"),
        *extra,
    ]
    rc = volume_cli.main(argv)
    return rc, out


class TestVolumeCLI:
    @pytest.mark.slow
    def test_end_to_end_jpeg_pairs(self, tmp_path):
        rc, out = _run(tmp_path)
        assert rc == 0
        jpgs = sorted(p.name for p in (out / "PGBM-0001").glob("*.jpg"))
        assert len(jpgs) == 8  # 4 slices x (original + processed)
        payload = json.loads((out / "res.json").read_text())
        assert payload["mode"] == "volume" and not payload["z_sharded"]
        assert payload["patients"]["PGBM-0001"]["slices"] == 4
        assert payload["patients"]["PGBM-0001"]["mask_voxels"] > 0

    @pytest.mark.slow
    def test_zsharded_matches_single_device(self, tmp_path):
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-virtual-device CPU mesh")
        rc1, out1 = _run(tmp_path / "a")
        # 4 slices over an 8-way z mesh forces the filler-plane padding path
        rc2, out2 = _run(tmp_path / "b", "--z-shard", "--export-mhd")
        assert rc1 == 0 and rc2 == 0
        for pid in ("PGBM-0001", "PGBM-0002"):
            r1 = json.loads((out1 / "res.json").read_text())["patients"][pid]
            r2 = json.loads((out2 / "res.json").read_text())["patients"][pid]
            assert r1["mask_voxels"] == r2["mask_voxels"], pid
            mask, _ = read_metaimage(out2 / pid / "mask.mhd")
            assert mask.sum() == r2["mask_voxels"]

    def test_compressed_mhd_export_round_trips(self, tmp_path):
        rc, out = _run(tmp_path, "--export-mhd", "--mhd-compressed")
        assert rc == 0
        pid = "PGBM-0001"
        assert (out / pid / "mask.zraw").exists()
        assert not (out / pid / "mask.raw").exists()
        mask, _ = read_metaimage(out / pid / "mask.mhd")
        rec = json.loads((out / "res.json").read_text())["patients"][pid]
        assert mask.sum() == rec["mask_voxels"]

    def test_resume_skips_completed_patients(self, tmp_path, capsys):
        rc, out = _run(tmp_path)
        assert rc == 0
        capsys.readouterr()
        rc = volume_cli.main(
            [
                "--synthetic", "2",
                "--synthetic-slices", "4",
                "--output", str(out),
                "--resume",
            ]
        )
        assert rc == 0
        text = capsys.readouterr().out
        assert text.count("already complete, skipping") == 2

    @pytest.mark.slow
    def test_resume_accounts_for_permanently_bad_slices(self, tmp_path, capsys):
        # a patient with one unreadable slice must still skip on resume
        # (regression: listing-stems vs usable-stems mismatch re-ran forever)
        rc, out = _run(tmp_path)
        assert rc == 0
        bad = next((out / "synthetic-cohort-2x4-256" / "PGBM-0001").rglob("*.dcm"))
        bad.write_bytes(b"junk")
        capsys.readouterr()
        args = [
            "--synthetic", "2", "--synthetic-slices", "4", "--output", str(out),
        ]
        assert volume_cli.main(args) == 0  # re-run visits + records the bad slice
        capsys.readouterr()
        assert volume_cli.main(args + ["--resume"]) == 0
        text = capsys.readouterr().out
        assert text.count("already complete, skipping") == 2

    def test_patient_failure_contained(self, tmp_path):
        rc, out = _run(tmp_path)
        assert rc == 0
        # wreck one patient's series entirely: every slice unreadable
        for f in (out / "synthetic-cohort-2x4-256" / "PGBM-0001").rglob("*.dcm"):
            f.write_bytes(b"junk")
        rc = volume_cli.main(
            [
                "--synthetic", "2",
                "--synthetic-slices", "4",
                "--output", str(out),
                "--results-json", str(out / "res2.json"),
            ]
        )
        assert rc == 1  # failure reported...
        payload = json.loads((out / "res2.json").read_text())
        assert "PGBM-0002" in payload["patients"]  # ...but the run continued
        assert "PGBM-0001" not in payload["patients"]


class TestVolumeTruncation:
    def test_truncated_patient_recomputed_on_resume(self, tmp_path, capsys):
        """A cap-truncated volume records STATUS_TRUNCATED (not DONE), so a
        --resume rerun with the cap raised recomputes the patient and the
        record comes back clean (VERDICT r4 item 4, volume driver)."""
        rc, out = _run(
            tmp_path, "--grow-block-iters", "1", "--grow-max-iters", "2"
        )
        assert rc == 0
        rec = json.loads((out / "res.json").read_text())
        assert rec["grow_truncated_patients"], "tiny cap must truncate"
        capsys.readouterr()
        rc = volume_cli.main(
            [
                "--synthetic", "2", "--synthetic-slices", "4",
                "--output", str(out),
                "--results-json", str(out / "res.json"),
                "--resume",
            ]
        )
        assert rc == 0
        text = capsys.readouterr().out
        assert "already complete, skipping" not in text
        rec2 = json.loads((out / "res.json").read_text())
        assert rec2["grow_truncated_patients"] == []


class TestMultiframeSeries:
    def test_single_multiframe_file_expands_to_z_stack(self, tmp_path, capsys):
        """A series stored as ONE multi-frame file (real-archive shape) is
        its own z-stack: frames become planes, stems get _fNNN suffixes,
        and the full driver exports a pair per frame."""
        import shutil

        golden = (
            pathlib.Path(__file__).parent / "golden" / "dicom"
            / "gdcm16_multiframe.dcm"
        )
        root = tmp_path / "cohort"
        series = root / "PGBM-0001" / "seriesA"
        series.mkdir(parents=True)
        shutil.copy(golden, series / "1-1.dcm")

        from nm03_capstone_project_tpu.cli.volume import _load_volume
        from nm03_capstone_project_tpu.config import PipelineConfig

        cfg = PipelineConfig(canvas=64, min_dim=16)
        vol, dims, stems, skipped = _load_volume(root, "PGBM-0001", cfg)
        assert vol.shape == (3, 64, 64)
        assert list(dims) == [32, 28]
        assert stems == ["1-1_f000", "1-1_f001", "1-1_f002"]
        assert skipped == []
        # frames differ (the generator XORs the frame index into low bytes)
        assert not (vol[0] == vol[1]).all()

        out = tmp_path / "out"
        rc = volume_cli.main(
            [
                "--base-path", str(root),
                "--output", str(out),
                "--canvas", "64",
                "--min-dim", "16",
                "--results-json", str(out / "res.json"),
            ]
        )
        assert rc == 0
        jpgs = sorted(p.name for p in (out / "PGBM-0001").glob("*.jpg"))
        assert len(jpgs) == 6  # 3 frames x (original, processed)
        assert "1-1_f002_original.jpg" in jpgs
