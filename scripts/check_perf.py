#!/usr/bin/env python
"""Perf-regression tripwires over a serving metrics snapshot (ISSUE 16).

Joins a replica's post-drain metrics snapshot (``--metrics``, schema
``nm03.metrics.v1``) against a committed perf baseline (``--baseline``,
schema ``nm03.perf_baseline.v1``, written by ``bench.py
--write-perf-baseline`` or ``--write-baseline`` below) and exits non-zero
when the run's device-time ledger drifted outside the baseline's tolerance
bands. The last mile of the ledger: the per-request device-seconds
histogram and the stage-share pie are live observability; this script is
what makes them a GATE — a stage that silently doubled, or a per-request
cost that jumped an order of magnitude, fails the drill instead of
scrolling past on a dashboard.

Usage:
    python scripts/check_perf.py --metrics m.json --baseline PERF_BASELINE.json
    python scripts/check_perf.py --metrics m.json --write-baseline PERF_BASELINE.json

Checked tripwires (each prints ``PERF DRIFT <where>: <msg>`` on failure):

* **per-request device cost** — the observed mean of the
  ``serving_device_seconds_per_request`` histogram (sum/count) against the
  baseline's ``device_seconds_per_slice``, as a RATIO band: fail when
  observed > baseline * (1 + device_seconds_rel) or observed <
  baseline / (1 + device_seconds_rel). Relative and symmetric in
  log-space, because device-seconds swing with host load — the band is
  wide by design (an order-of-magnitude tripwire, not a jitter alarm),
  and "suspiciously fast" trips too: a 10x drop means the ledger stopped
  measuring, not that the code got 10x faster.
* **stage shares** — each ``serving_device_time_share{stage}`` gauge
  against the baseline's ``stage_shares[stage]``, as an ABSOLUTE band:
  fail when |observed - baseline| > stage_share_abs. Only stages whose
  baseline share >= ``min_share`` are gated — a 0.4% stage's share is
  noise, and gating it would flake; shares are already normalized so the
  absolute band is scale-free.
* **presence** — a baseline with stage shares requires the snapshot to
  carry the share gauges at all (a run whose sampler never fired gates
  nothing, and must say so rather than pass vacuously). The histogram
  tripwire is likewise only vacuous when the baseline carries no
  ``device_seconds_per_slice``.

``--write-baseline PATH`` derives a fresh baseline FROM the snapshot
instead of checking it (observed mean + observed shares + default bands)
— the re-pin workflow after an intentional perf change, from the same
artifact the failing gate read.

Exit codes: 0 ok, 1 perf drift, 2 usage/unreadable artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

SCHEMA_METRICS = "nm03.metrics.v1"
SCHEMA_BASELINE = "nm03.perf_baseline.v1"

DEVICE_SECONDS_HIST = "serving_device_seconds_per_request"
STAGE_SHARE_GAUGE = "serving_device_time_share"

# bands a --write-baseline re-pin starts from (wide by design: tripwire,
# not jitter alarm — see the module docstring)
DEFAULT_DEVICE_SECONDS_REL = 4.0
DEFAULT_STAGE_SHARE_ABS = 0.25
DEFAULT_MIN_SHARE = 0.05


def _load_json(path: str, what: str):
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_perf: {what} {path} unreadable: {e}", file=sys.stderr)
        return None
    if not isinstance(doc, dict):
        print(f"check_perf: {what} {path} is not a JSON object",
              file=sys.stderr)
        return None
    return doc


def observed_from_snapshot(snap: dict) -> dict:
    """The ledger evidence inside one metrics snapshot.

    Returns ``{"device_seconds_mean": float|None, "request_count": int,
    "stage_shares": {stage: value}}`` — the mean from the per-request
    histogram's sum/count (None until any request was observed), the
    shares from the pie gauges (empty until the sampler reduced a
    capture).
    """
    mean = None
    count = 0
    shares: dict = {}
    for rec in snap.get("metrics") or []:
        if not isinstance(rec, dict):
            continue
        name, kind = rec.get("name"), rec.get("type")
        if name == DEVICE_SECONDS_HIST and kind == "histogram":
            c = rec.get("count")
            s = rec.get("sum")
            if isinstance(c, (int, float)) and isinstance(s, (int, float)):
                count += int(c)
                if c:
                    mean = (0.0 if mean is None else mean) + float(s)
        elif name == STAGE_SHARE_GAUGE and kind == "gauge":
            stage = (rec.get("labels") or {}).get("stage")
            v = rec.get("value")
            if stage and isinstance(v, (int, float)):
                shares[str(stage)] = float(v)
    if mean is not None and count:
        mean = mean / count
    return {
        "device_seconds_mean": mean,
        "request_count": count,
        "stage_shares": shares,
    }


def check(baseline: dict, observed: dict) -> list:
    """The tripwire verdicts; returns the list of drift messages."""
    problems: list = []
    tol = baseline.get("tolerance") or {}
    rel = float(tol.get("device_seconds_rel", DEFAULT_DEVICE_SECONDS_REL))
    abs_band = float(tol.get("stage_share_abs", DEFAULT_STAGE_SHARE_ABS))
    min_share = float(baseline.get("min_share", DEFAULT_MIN_SHARE))

    base_ds = baseline.get("device_seconds_per_slice")
    obs_ds = observed.get("device_seconds_mean")
    if isinstance(base_ds, (int, float)) and base_ds > 0:
        if obs_ds is None:
            problems.append(
                "device_seconds: no serving_device_seconds_per_request "
                "observations in the snapshot — the ledger never charged a "
                "request, nothing to gate (did the drill serve traffic?)"
            )
        else:
            ratio = obs_ds / float(base_ds)
            hi = 1.0 + rel
            lo = 1.0 / (1.0 + rel)
            if ratio > hi or ratio < lo:
                problems.append(
                    f"device_seconds: observed mean {obs_ds:.6g}s/request is "
                    f"{ratio:.3g}x the baseline {base_ds:.6g}s/slice, "
                    f"outside [{lo:.3g}x..{hi:.3g}x] "
                    f"(device_seconds_rel={rel:g})"
                )

    base_shares = baseline.get("stage_shares") or {}
    gated = {
        st: float(v) for st, v in base_shares.items()
        if isinstance(v, (int, float)) and v >= min_share
    }
    obs_shares = observed.get("stage_shares") or {}
    if gated and not obs_shares:
        problems.append(
            f"stage_shares: baseline gates {sorted(gated)} but the snapshot "
            f"carries no {STAGE_SHARE_GAUGE} series — the profile sampler "
            "never reduced a capture (sampler off, or the drill outpaced "
            "its first cadence tick)"
        )
    elif obs_shares:
        for st, want in sorted(gated.items()):
            got = obs_shares.get(st, 0.0)
            if abs(got - want) > abs_band:
                problems.append(
                    f"stage_shares[{st}]: observed {got:.4f} vs baseline "
                    f"{want:.4f}, |delta| {abs(got - want):.4f} > "
                    f"stage_share_abs {abs_band:g}"
                )
    return problems


def write_baseline(path: str, observed: dict, device_kind: str) -> int:
    """Derive and atomically write a fresh baseline from a snapshot."""
    if observed["device_seconds_mean"] is None and not observed["stage_shares"]:
        print(
            "check_perf: snapshot carries neither per-request histogram "
            "observations nor stage-share gauges — nothing to baseline",
            file=sys.stderr,
        )
        return 2
    baseline = {
        "schema": SCHEMA_BASELINE,
        "device_kind": device_kind,
        "device_seconds_per_slice": (
            None if observed["device_seconds_mean"] is None
            else round(observed["device_seconds_mean"], 9)
        ),
        "stage_shares": {
            st: round(v, 4)
            for st, v in sorted(observed["stage_shares"].items())
        },
        "tolerance": {
            "device_seconds_rel": DEFAULT_DEVICE_SECONDS_REL,
            "stage_share_abs": DEFAULT_STAGE_SHARE_ABS,
        },
        "min_share": DEFAULT_MIN_SHARE,
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    print(f"check_perf: wrote {path}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--metrics", required=True,
        help="metrics snapshot JSON (nm03.metrics.v1) to gate — a serving "
        "drill's post-drain --metrics-out artifact",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="committed perf baseline (nm03.perf_baseline.v1) to gate "
        "against",
    )
    ap.add_argument(
        "--write-baseline", default=None, metavar="PATH",
        help="derive a fresh baseline FROM the snapshot and write it here "
        "instead of checking (the re-pin workflow after an intentional "
        "perf change)",
    )
    ap.add_argument(
        "--device-kind", default="unknown",
        help="device_kind stamped into a --write-baseline output "
        "(snapshots don't carry it; bench-derived baselines do)",
    )
    args = ap.parse_args(argv)
    if bool(args.baseline) == bool(args.write_baseline):
        ap.error("pass exactly one of --baseline / --write-baseline")

    snap = _load_json(args.metrics, "metrics snapshot")
    if snap is None:
        return 2
    if snap.get("schema") != SCHEMA_METRICS:
        print(
            f"check_perf: {args.metrics} schema {snap.get('schema')!r} != "
            f"{SCHEMA_METRICS!r}",
            file=sys.stderr,
        )
        return 2
    observed = observed_from_snapshot(snap)

    if args.write_baseline:
        return write_baseline(args.write_baseline, observed, args.device_kind)

    baseline = _load_json(args.baseline, "baseline")
    if baseline is None:
        return 2
    if baseline.get("schema") != SCHEMA_BASELINE:
        print(
            f"check_perf: {args.baseline} schema {baseline.get('schema')!r} "
            f"!= {SCHEMA_BASELINE!r}",
            file=sys.stderr,
        )
        return 2

    problems = check(baseline, observed)
    for p in problems:
        print(f"PERF DRIFT {p}", file=sys.stderr)
    if problems:
        print(f"check_perf: {len(problems)} drift(s) vs {args.baseline}",
              file=sys.stderr)
        return 1
    print(
        f"check_perf: OK ({args.metrics} vs {args.baseline}: "
        f"{observed['request_count']} requests, "
        f"{len(observed['stage_shares'])} stage shares)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
