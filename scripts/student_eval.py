"""Cohort-scale teacher-vs-student deployment eval -> results/student_eval.json.

VERDICT r2 item 7: the distillation stack existed and deployed (--model on
both 2D batch drivers), but no committed record measured the student's
accuracy at deployment scale. This script closes that: it trains the 2D
student against the classical-pipeline teacher, deploys it through BOTH
batch drivers (CohortProcessor sequential + parallel — the real driver
paths: discovery, DICOM decode, manifests, JPEG export) over the synthetic
cohort, and records teacher-vs-student IoU per driver mode plus wall
throughput, using the runner's ``mask_sink`` hook so the comparison is over
exactly the masks the drivers export.

CPU-sized defaults (minibatched training; XLA:CPU full-batch steps at
deployment scale run ~33 s). The TPU revalidation pass
(scripts/tpu_revalidate.sh) reruns it chip-sized:

    python scripts/student_eval.py --steps 300 --minibatch 0

Writes ``--out`` (default results/student_eval.json) via
utils.timing.write_results_json, so the record carries the git SHA.
"""

from __future__ import annotations

import argparse
import sys
import tempfile
import threading
import time
from pathlib import Path

import numpy as np

# Self-locating: runnable as `python scripts/student_eval.py` even when the
# package is not installed (sys.path[0] is scripts/, not the repo root).
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--patients", type=int, default=20)
    ap.add_argument("--slices", type=int, default=22, help="slices per patient")
    ap.add_argument("--train-slices", type=int, default=128,
                    help="training subset size (the eval still runs the full cohort)")
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--minibatch", type=int, default=16,
                    help="per-step minibatch; 0 = full batch")
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--base-channels", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="results/student_eval.json")
    return ap.parse_args(argv)


def _collect_run(cohort_root, out_dir, cfg, mode, model_params=None):
    """One driver run; returns ({(pid, stem): bool mask}, summary, wall_s)."""
    from nm03_capstone_project_tpu.cli.runner import CohortProcessor
    from nm03_capstone_project_tpu.config import BatchConfig

    masks: dict = {}
    lock = threading.Lock()

    def sink(pid, stem, mask):
        with lock:  # parallel mode calls from IO-pool threads
            masks[(pid, stem)] = np.asarray(mask).astype(bool)

    proc = CohortProcessor(
        cohort_root,
        out_dir,
        cfg=cfg,
        batch_cfg=BatchConfig(),
        mode=mode,
        model_params=model_params,
        mask_sink=sink,
    )
    t0 = time.perf_counter()
    summary = proc.process_all_patients()
    return masks, summary, time.perf_counter() - t0


def main(argv=None) -> int:
    args = parse_args(argv)
    t_start = time.perf_counter()

    import shutil

    import jax

    from nm03_capstone_project_tpu.config import PipelineConfig

    cfg = PipelineConfig()
    backend = jax.devices()[0].platform
    print(f"backend: {backend} ({jax.devices()[0].device_kind})")

    root = Path(tempfile.mkdtemp(prefix="student_eval_cohort_"))
    scratch = Path(tempfile.mkdtemp(prefix="student_eval_out_"))
    try:
        return _run_eval(args, cfg, backend, root, scratch, t_start)
    finally:
        # the revalidation pass reruns this on every chip window; leaked
        # cohorts + 4 full export trees per run would fill /tmp
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(scratch, ignore_errors=True)


def _run_eval(args, cfg, backend, root, scratch, t_start) -> int:
    import jax

    from nm03_capstone_project_tpu.cli.runner import decode_and_guard
    from nm03_capstone_project_tpu.data.discovery import (
        find_patient_dirs,
        load_dicom_files_for_patient,
    )
    from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort
    from nm03_capstone_project_tpu.models import (
        distill_batch,
        init_unet,
        prepare_student_inputs,
    )
    from nm03_capstone_project_tpu.models.train import make_optimizer, train_step
    from nm03_capstone_project_tpu.utils.timing import write_results_json

    write_synthetic_cohort(
        root, n_patients=args.patients, n_slices=args.slices, seed=args.seed
    )

    # ---- teacher labels + training subset --------------------------------
    pixels, dims = [], []
    for pid in find_patient_dirs(root):
        for f in load_dicom_files_for_patient(root, pid):
            if len(pixels) >= args.train_slices:
                break
            px = decode_and_guard(f, cfg)
            if px is None:
                continue
            canvas = np.zeros((cfg.canvas, cfg.canvas), np.float32)
            canvas[: px.shape[0], : px.shape[1]] = px
            pixels.append(canvas)
            dims.append(px.shape)
    px = np.stack(pixels)
    dm = np.asarray(dims, np.int32)
    t0 = time.perf_counter()
    labels = np.asarray(distill_batch(px, dm, cfg))
    label_s = time.perf_counter() - t0
    print(f"teacher labels: {len(px)} slices in {label_s:.1f}s "
          f"({labels.sum()} positive voxels)")

    # ---- distillation -----------------------------------------------------
    x = np.asarray(prepare_student_inputs(px, cfg))
    params = init_unet(jax.random.PRNGKey(args.seed), base=args.base_channels)
    tx = make_optimizer(args.lr, total_steps=args.steps)
    opt = tx.init(params)
    rng = np.random.default_rng(args.seed)
    losses = []
    t0 = time.perf_counter()
    for step in range(args.steps):
        if args.minibatch and args.minibatch < len(x):
            idx = rng.choice(len(x), args.minibatch, replace=False)
            bx, bl, bd = x[idx], labels[idx], dm[idx]
        else:
            bx, bl, bd = x, labels, dm
        params, opt, loss = train_step(params, opt, bx, bl, bd, tx=tx)
        losses.append(float(loss))
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {losses[-1]:.4f}", flush=True)
    train_s = time.perf_counter() - t0
    if losses[-1] >= losses[0]:
        print("WARNING: training loss did not improve", file=sys.stderr)

    # ---- deployment eval through both drivers ----------------------------
    record = {
        "backend": backend,
        "cohort": {"patients": args.patients, "slices_per_patient": args.slices},
        "train": {
            "slices": len(px),
            "steps": args.steps,
            "minibatch": args.minibatch,
            "base_channels": args.base_channels,
            "loss_first": round(losses[0], 4),
            "loss_last": round(losses[-1], 4),
            "label_s": round(label_s, 1),
            "train_s": round(train_s, 1),
        },
        "modes": {},
    }
    for mode in ("sequential", "parallel"):
        teacher, t_sum, t_wall = _collect_run(root, scratch / f"t-{mode}", cfg, mode)
        student, s_sum, s_wall = _collect_run(
            root, scratch / f"s-{mode}", cfg, mode, model_params=params
        )
        common_keys = sorted(set(teacher) & set(student))
        inter = union = 0
        per_patient: dict = {}
        for key in common_keys:
            t, s = teacher[key], student[key]
            pi, pu = int((t & s).sum()), int((t | s).sum())
            inter += pi
            union += pu
            acc = per_patient.setdefault(key[0], [0, 0])
            acc[0] += pi
            acc[1] += pu
        # a zero union (no slices compared, or all-empty masks on both
        # sides) is a FAILED comparison, scored 0 — never NaN, which would
        # both slip past the min() gate below and break strict-JSON readers
        iou = inter / union if union else 0.0
        patient_ious = sorted(
            i / u for i, u in per_patient.values() if u
        )
        record["modes"][mode] = {
            "iou": round(iou, 4),
            "degenerate": union == 0,
            "patient_iou_min": round(patient_ious[0], 4) if patient_ious else None,
            "patient_iou_median": (
                round(patient_ious[len(patient_ious) // 2], 4)
                if patient_ious else None
            ),
            "slices_compared": len(common_keys),
            "teacher_ok": t_sum.succeeded_slices,
            "student_ok": s_sum.succeeded_slices,
            "teacher_slices_per_s": round(t_sum.succeeded_slices / t_wall, 2),
            "student_slices_per_s": round(s_sum.succeeded_slices / s_wall, 2),
        }
        print(f"{mode}: IoU {iou:.4f} over {len(common_keys)} slices "
              f"(teacher {t_wall:.1f}s, student {s_wall:.1f}s)")

    record["elapsed_s"] = round(time.perf_counter() - t_start, 1)
    write_results_json(args.out, record)
    print(f"wrote {args.out}")
    worst = min(m["iou"] for m in record["modes"].values())
    return 0 if worst > 0.5 else 1


if __name__ == "__main__":
    sys.exit(main())
