#!/usr/bin/env python3
"""Per-stage bench regression gate.

Diffs a bench record's per-stage ``ms_per_batch`` (the ``stages`` table
``bench.py`` emits, either standalone or wrapped in a driver capture's
``parsed`` field, or a ``--results-json`` payload embedding it) against the
``stage_baseline`` section of BASELINE.json, and exits non-zero when any
stage regressed by more than ``--threshold`` (default 10%).

Pure stdlib / pure JSON — safe to run in CI or from the bench orchestrator
host without touching jax. Comparisons are same-backend only: a CPU record
diffed against a TPU baseline (or vice versa) is meaningless and exits 0
with a note, so a wedged-tunnel round cannot fail the gate against chip
numbers.

Exit codes: 0 = no regression (or nothing comparable), 1 = regression,
2 = unreadable/invalid input.

``--update`` rewrites BASELINE.json's ``stage_baseline`` from the given
record instead of comparing — run it after a deliberate perf change lands
so the gate tracks the new floor.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_bench_regression: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(2)


def extract_stages(record: dict):
    """(backend, {stage: ms_per_batch}) from any bench record shape.

    Accepts the bench's own emitted/banked record, a driver capture
    (``{"parsed": {...}}``), or a results JSON that embedded the record.
    Returns (None, {}) when no stage table is present.
    """
    if not isinstance(record, dict):
        return None, {}
    if "stages" not in record and isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    stages = record.get("stages")
    if not isinstance(stages, dict):
        return None, {}
    out = {}
    for name, entry in stages.items():
        if isinstance(entry, dict) and "ms_per_batch" in entry:
            out[name] = float(entry["ms_per_batch"])
    return record.get("backend") or record.get("device_kind"), out


def compare(baseline: dict, current: dict, threshold: float):
    """List of (stage, base_ms, cur_ms, ratio) regressions past threshold."""
    regressions = []
    for name, base_ms in baseline.items():
        cur_ms = current.get(name)
        if cur_ms is None or base_ms <= 0:
            continue
        ratio = cur_ms / base_ms
        if ratio > 1.0 + threshold:
            regressions.append((name, base_ms, cur_ms, ratio))
    return regressions


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("results", help="bench record / driver capture JSON")
    parser.add_argument(
        "--baseline",
        default="BASELINE.json",
        help="baseline file holding the stage_baseline section",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="fractional regression that fails the gate (default 0.10)",
    )
    parser.add_argument(
        "--update",
        action="store_true",
        help="write the record's stages into the baseline instead of comparing",
    )
    args = parser.parse_args(argv)

    record = _load_json(args.results)
    backend, current = extract_stages(record)
    if not current:
        print(
            f"check_bench_regression: no stage table in {args.results}; "
            "nothing to gate",
        )
        return 0

    base_doc = _load_json(args.baseline)
    if args.update:
        base_doc["stage_baseline"] = {
            "backend": backend,
            "source": args.results,
            "ms_per_batch": current,
        }
        # tmp+rename (NM351): BASELINE.json is the regression gate's truth;
        # updating it must be all-or-nothing
        tmp = f"{args.baseline}.tmp"
        with open(tmp, "w") as f:
            json.dump(base_doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, args.baseline)
        print(
            f"check_bench_regression: baseline updated from {args.results} "
            f"({len(current)} stages, backend {backend})"
        )
        return 0

    section = base_doc.get("stage_baseline") or {}
    base_stages = section.get("ms_per_batch") or {}
    if not base_stages:
        print(
            f"check_bench_regression: {args.baseline} has no stage_baseline "
            "section; run with --update to seed it",
        )
        return 0
    base_backend = section.get("backend")
    if base_backend and backend and base_backend != backend:
        print(
            f"check_bench_regression: backend mismatch (baseline "
            f"{base_backend}, record {backend}); cross-backend stage times "
            "are not comparable — skipping"
        )
        return 0

    regressions = compare(base_stages, current, args.threshold)
    for name, base_ms, cur_ms, ratio in regressions:
        print(
            f"REGRESSION {name}: {base_ms:.3f} -> {cur_ms:.3f} ms/batch "
            f"({(ratio - 1) * 100:.1f}% > {args.threshold * 100:.0f}%)"
        )
    improved = [
        n for n, b in base_stages.items()
        if n in current and current[n] < b
    ]
    print(
        f"check_bench_regression: {len(regressions)} regression(s) over "
        f"{args.threshold * 100:.0f}% across {len(base_stages)} baseline "
        f"stage(s); {len(improved)} improved"
    )
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
