#!/usr/bin/env python
"""The one-command static gate: nm03-lint + ruff, CI-style.

Mirrors ``check_telemetry.py``'s role for run artifacts: a single script
that exits non-zero when the codebase drifts from its checked contracts.

Phases (each independently reported, all must pass):

1. **parse** — every tracked .py file compiles (the cheapest possible
   smoke; a syntax error should fail THIS gate, not whatever imports the
   file first);
2. **nm03-lint** — the project rules (docs/STATIC_ANALYSIS.md) against the
   checked-in baseline (``nm03lint_baseline.json``); any NEW finding
   fails. ``--update-baseline`` forwards to nm03-lint (use after fixing or
   deliberately accepting findings; the baseline diff is the review
   artifact);
3. **ruff** — the general-purpose layer (config in ``pyproject.toml``),
   run only when ruff is installed: the container this repo grows in does
   not ship it, and a gate that fails on missing tooling rather than bad
   code would train everyone to ignore it. When absent, the phase reports
   SKIPPED loudly instead of passing silently;
4. **lockdep witness** (``--lockdep-witness PATH``, optional) — gate a
   ``lockdep_witness.json`` produced by an instrumented serving run
   (``NM03_LOCKDEP=1``, utils/lockdep.py) against the static may-hold
   graph: zero inversions, zero observed cycles, every observed edge
   either statically derivable or targeting an obs/ leaf lock. The
   runtime face of NM421 (docs/STATIC_ANALYSIS.md).

Usage:
    python scripts/check_static.py
    python scripts/check_static.py --update-baseline
    python scripts/check_static.py --skip-ruff
    python scripts/check_static.py --lockdep-witness results/lockdep_witness.json
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def run_parse_phase() -> int:
    """py_compile every package/scripts file; count failures."""
    import py_compile

    failures = 0
    roots = [REPO / "nm03_capstone_project_tpu", REPO / "scripts", REPO / "bench.py"]
    for root in roots:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts:
                continue
            try:
                py_compile.compile(str(f), cfile=None, doraise=True)
            except py_compile.PyCompileError as e:
                print(f"parse: {e.msg}")
                failures += 1
    return failures


def run_lint_phase(update_baseline: bool) -> int:
    cmd = [
        sys.executable,
        "-m",
        "nm03_capstone_project_tpu.analysis.cli",
        "--root",
        str(REPO),
        "--format",
        "json",
    ]
    if update_baseline:
        cmd = cmd[:-2] + ["--update-baseline"]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, cwd=REPO, timeout=300
    )
    if update_baseline:
        print(proc.stdout.strip() or proc.stderr.strip())
        return proc.returncode
    try:
        payload = json.loads(proc.stdout)
    except json.JSONDecodeError:
        print(f"nm03-lint: unparseable output (rc={proc.returncode}):")
        print(proc.stdout[-2000:] or proc.stderr[-2000:])
        return 1
    for f in payload.get("findings", []):
        print(f"nm03-lint: {f['path']}:{f['line']}: {f['rule']} {f['message']}")
    n = len(payload.get("findings", []))
    print(
        f"nm03-lint: {n} new finding(s), {payload.get('baselined', 0)} "
        f"baselined, {payload.get('files_scanned', 0)} files"
    )
    return n


def run_ruff_phase(skip: bool) -> int:
    """ruff check . when available; loud SKIP when not installed."""
    if skip:
        print("ruff: skipped (--skip-ruff)")
        return 0
    probe = subprocess.run(
        [sys.executable, "-m", "ruff", "--version"],
        capture_output=True,
        text=True,
    )
    if probe.returncode != 0:
        print(
            "ruff: SKIPPED — not installed in this environment "
            "(pyproject.toml [tool.ruff] is the config it will use)"
        )
        return 0
    proc = subprocess.run(
        [sys.executable, "-m", "ruff", "check", "."],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    out = (proc.stdout or "") + (proc.stderr or "")
    if proc.returncode != 0:
        print(out.strip())
        return 1
    print("ruff: clean")
    return 0


def run_lockdep_phase(witness_path) -> int:
    """Gate an observed-lock-order witness against the static graph."""
    if not witness_path:
        return 0
    if str(REPO) not in sys.path:
        sys.path.insert(0, str(REPO))
    from nm03_capstone_project_tpu.analysis import lockorder
    from nm03_capstone_project_tpu.analysis.core import collect_files

    p = Path(witness_path)
    if not p.exists():
        print(f"lockdep: witness file not found: {p}")
        return 1
    try:
        witness = json.loads(p.read_text())
    except json.JSONDecodeError as e:
        print(f"lockdep: unparseable witness {p}: {e}")
        return 1
    files = collect_files(
        [REPO / "nm03_capstone_project_tpu", REPO / "scripts", REPO / "bench.py"],
        REPO,
    )
    graph = lockorder.build_lock_graph(files)
    problems = lockorder.explain_witness(witness, graph)
    for prob in problems:
        print(f"lockdep: {prob}")
    if problems:
        return len(problems)
    print(
        f"lockdep: witness OK — {len(witness.get('edges', []))} edge(s) over "
        f"{len(witness.get('sites', []))} site(s), 0 inversions, 0 cycles"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="forward to nm03-lint: absorb current findings into the baseline",
    )
    p.add_argument(
        "--skip-ruff", action="store_true", help="skip the ruff phase"
    )
    p.add_argument(
        "--lockdep-witness",
        default=None,
        metavar="JSON",
        help="gate a utils/lockdep.py witness against the static "
        "may-hold graph (analysis/lockorder.py)",
    )
    args = p.parse_args(argv)

    failures = 0
    parse_failures = run_parse_phase()
    print(f"parse: {'clean' if not parse_failures else f'{parse_failures} failure(s)'}")
    failures += parse_failures
    failures += run_lint_phase(args.update_baseline)
    failures += run_ruff_phase(args.skip_ruff)
    failures += run_lockdep_phase(args.lockdep_witness)
    if failures:
        print(f"check_static: FAIL ({failures} problem(s))")
        return 1
    print("check_static: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
