#!/usr/bin/env python
"""Thin shim: ``python scripts/loadgen.py`` == ``nm03-loadgen``.

The implementation lives in :mod:`nm03_capstone_project_tpu.serving.loadgen`
(so the ``nm03-loadgen`` console script can import it); this file exists so
the scripts/ directory stays the one-stop home of runnable tooling
(check_telemetry.py, check_bench_regression.py, ...).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nm03_capstone_project_tpu.serving.loadgen import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
