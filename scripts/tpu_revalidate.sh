#!/bin/bash
# One-shot TPU revalidation: run after the accelerator tunnel recovers.
#
# Refreshes every chip-measured artifact with the CURRENT code, ordered by
# marginal value so a mid-pass re-wedge costs the least-novel records first:
#   1. bench.py            -> results/bench_tpu_<date>.json (headline +
#                             stages incl. device_ms/roofline — the round's
#                             single most important artifact)
#   2. nm03-volume         -> results/results_volume.json (the 3D path has
#                             never had a chip record)
#   3. nm03-parallel       -> results/results_parallel.json (fast)
#   4. nm03-sequential     -> results/results_sequential.json (slowest:
#                             tunnel-latency-bound per slice)
#   5. student_eval.py     -> results/student_eval.json (teacher-vs-student
#                             IoU through both drivers, chip-sized training)
#
# Everything is sequenced (one chip; concurrent runs would contend) and each
# step tolerates failure so a mid-run tunnel wedge still leaves the earlier
# artifacts on disk. Run from the repo root.
set -u
cd "$(dirname "$0")/.."
STAMP=$(date -u +%Y%m%d)

echo "== probe =="
timeout 90 python bench.py --probe || { echo "tunnel not healthy; aborting"; exit 1; }

echo "== bench =="
# the probe above already gated on tunnel health, so cap bench's internal
# wedge-recovery vigil (NM03_BENCH_VIGIL_BUDGET_S) — a mid-run wedge should
# fail fast here and leave the chip window to the other drivers below.
# timeout(1) sends SIGTERM, which bench.py catches to emit best-so-far.
# stdout now carries the SLIM driver line; the FULL record (all legs +
# probe history) is the atomically-banked results/bench_partial.json —
# that is what gets stamped as the round's chip artifact. Remove any
# STALE partial first (bench.py unlinks it too, but only once main()
# runs — an import-time crash must not let a previous run masquerade
# as this one), and keep stdout under results/ as the fallback record.
rm -f results/bench_partial.json
timeout 1800 env NM03_BENCH_VIGIL_BUDGET_S=600 \
  python bench.py > "results/bench_stdout_${STAMP}.log" 2>bench_stderr.log \
  || echo "bench failed; see bench_stderr.log"
if python -c "import json; json.load(open('results/bench_partial.json'))" 2>/dev/null; then
  cp results/bench_partial.json "results/bench_tpu_${STAMP}.json"
  echo "banked results/bench_tpu_${STAMP}.json:"
  tail -c 600 "results/bench_tpu_${STAMP}.json"; echo
else
  # no banked record (results/ unwritable mid-run?): the slim stdout line
  # is the only measurement left — stamp that rather than nothing
  python - "results/bench_stdout_${STAMP}.log" "results/bench_tpu_${STAMP}.json" <<'PYEOF'
import json, sys
try:
    lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
    rec = json.loads(lines[-1])
    json.dump(rec, open(sys.argv[2], "w"))
    print("stamped slim stdout record (banked file was missing)")
except Exception as e:
    print(f"no record recoverable: {e}")
PYEOF
fi

echo "== volume driver =="
timeout 1200 python -m nm03_capstone_project_tpu.cli.volume \
  --synthetic 4 --synthetic-slices 8 --output /tmp/tpu-out-vol --export-mhd \
  --results-json results/results_volume.json >/tmp/tpu-vol.log 2>&1 \
  || echo "volume failed; see /tmp/tpu-vol.log"

echo "== parallel cohort =="
timeout 1200 python -m nm03_capstone_project_tpu.cli.parallel \
  --synthetic 20 --synthetic-slices 22 --output /tmp/tpu-out-par \
  --results-json results/results_parallel.json >/tmp/tpu-par.log 2>&1 \
  || echo "parallel failed; see /tmp/tpu-par.log"

echo "== sequential cohort =="
timeout 1500 python -m nm03_capstone_project_tpu.cli.sequential \
  --synthetic 20 --synthetic-slices 22 --output /tmp/tpu-out-seq \
  --results-json results/results_sequential.json >/tmp/tpu-seq.log 2>&1 \
  || echo "sequential failed; see /tmp/tpu-seq.log"

echo "== student deployment eval =="
# chip-sized: full-batch steps are cheap on the TPU (CPU needs minibatches).
# 2400 s: the round-4 run took 1778 s — 8 s inside the old 1800 s timeout.
timeout 2400 python scripts/student_eval.py --steps 300 --minibatch 0 \
  --train-slices 440 --out results/student_eval.json >/tmp/tpu-se.log 2>&1 \
  || echo "student eval failed; see /tmp/tpu-se.log"

echo "== summary =="
python - <<'EOF'
import json, pathlib
for f in sorted(pathlib.Path("results").glob("*.json")):
    try:
        d = json.loads(f.read_text())
    except Exception as e:
        print(f.name, "unreadable:", e); continue
    keys = {k: d[k] for k in ("backend", "value", "vs_baseline", "wall_s", "mode", "git_sha") if k in d}
    print(f.name, keys)
EOF
