"""Host-roofline for the export leg (VERDICT r4 item 3).

Measures, single-threaded on this host, the per-slice cost of every stage
the batch drivers' export path pays after the mask returns from the device:
render (NumPy and C++), JPEG encode (PIL/libjpeg-turbo and the in-tree C++
encoder), and the file write — then prints the implied single-core ceiling
in slices/s for the export leg alone. The cohort drivers overlap export
with device compute, so end-to-end throughput approaches min(device rate,
this ceiling) on a 1-core host.

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python scripts/export_roofline.py
"""

from __future__ import annotations

import io
import json
import tempfile
import time
from pathlib import Path

import numpy as np


def _time(fn, n=60):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n * 1e3


def main() -> None:
    from nm03_capstone_project_tpu import native
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.render.host_render import host_render_pair

    cfg = PipelineConfig()
    rng = np.random.default_rng(0)
    h = w = 240  # the synthetic cohort's slice size
    px = np.zeros((256, 256), np.float32)
    px[:h, :w] = rng.random((h, w), np.float32) * 4000
    mask = np.zeros((256, 256), np.uint8)
    mask[:h, :w] = (rng.random((h, w)) > 0.85).astype(np.uint8)
    dims = np.asarray([h, w], np.int32)

    out = {}
    out["render_numpy_ms"] = round(_time(lambda: host_render_pair(px, mask, dims, cfg)), 3)
    if native.available():
        out["render_native_ms"] = round(
            _time(lambda: native.render_pair_native(px, mask, dims, cfg)), 3
        )
    gray, seg = host_render_pair(px, mask, dims, cfg)

    from PIL import Image

    def pil_encode():
        b = io.BytesIO()
        Image.fromarray(gray, mode="L").save(b, format="jpeg", quality=90)

    out["encode_pil_ms"] = round(_time(pil_encode), 3)
    if native.available():
        out["encode_native_ms"] = round(
            _time(lambda: native.encode_jpeg_gray(gray, 90)), 3
        )

    with tempfile.TemporaryDirectory() as td:
        from nm03_capstone_project_tpu.render.export import save_jpeg

        p = Path(td) / "x.jpg"

        def full_write():
            save_jpeg(gray, p)
            save_jpeg(seg, p)

        out["write_pair_ms"] = round(_time(full_write), 3)

    render = out.get("render_native_ms", out["render_numpy_ms"])
    per_slice = render + out["write_pair_ms"]
    out["export_per_slice_ms"] = round(per_slice, 3)
    out["export_ceiling_slices_per_s"] = round(1000.0 / per_slice, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
