#!/usr/bin/env python
"""Validate run telemetry artifacts against the documented schemas.

Checks a JSONL event stream (``--events``, schema ``nm03.events.v1``) and/or
a metrics snapshot (``--metrics``, schema ``nm03.metrics.v1``) as written by
the CLI drivers' ``--log-json`` / ``--metrics-out`` flags and documented in
docs/OBSERVABILITY.md. Exits non-zero on any drift, printing one line per
violation — the CI gate that keeps producers and the documented schema from
diverging silently.

Usage:
    python scripts/check_telemetry.py --events run.jsonl --metrics m.json
    python scripts/check_telemetry.py --events run.jsonl --expect-patients 3

Validated invariants (the contract, not a style check):

events
  * every line parses as a JSON object with the full run envelope
    (schema, run_id, git_sha, seq, ts_unix, mono_s, level, event);
  * one run_id and one git_sha per stream; seq strictly increasing from 0;
    mono_s non-decreasing; level in the documented set;
  * first record is ``run_started``; last record is ``run_finished``;
  * exactly ONE terminal ``patient_outcome`` record per patient_id, with
    status in {ok, failed}, non-negative slice counts, boolean
    grow_truncated, integer retries, and error_class string-or-null;
  * ``grow_truncated`` and failed-patient outcomes carry level WARNING;
  * resilience events (docs/RESILIENCE.md): ``degraded`` is WARNING with a
    non-empty ``cause``; ``retry`` carries a non-empty ``cause`` and a
    positive integer ``attempt``; ``fault_injected`` carries non-empty
    ``site`` and ``kind`` strings.

metrics
  * envelope (schema, run_id, git_sha, created_unix, metrics list);
  * Prometheus-legal metric/label names; one type per metric name;
  * counters/gauges numeric, counters non-negative;
  * histogram buckets cumulative non-decreasing, ending in "+Inf" whose
    count equals the series count; sum numeric;
  * resilience counters carry their documented labels:
    ``resilience_retries_total{cause}``,
    ``resilience_faults_injected_total{site,kind}``,
    ``pipeline_degraded_total{cause}``;
  * ``--expect-counter NAME=MIN`` (repeatable) requires the summed value
    of NAME's series to be at least MIN — the chaos suite's assertion
    hook (e.g. ``--expect-counter pipeline_degraded_total=1``); the
    double-equals form ``NAME==VALUE`` is the gauge-compatible EXACT
    expectation: the counter must exist (absence fails, like a gauge) and
    total exactly VALUE. The compile-cache drills need both directions —
    ``compile_cache_hits_total==0`` proves a cold start really compiled,
    ``compile_cache_misses_total==0`` that a warm restart loaded
    everything (ISSUE 9);
  * ``--expect-histogram NAME=MINCOUNT`` (repeatable) requires the summed
    observation count across NAME's histogram series to be at least
    MINCOUNT — the serving load/chaos smoke's assertion hook (e.g.
    ``--expect-histogram serving_queue_wait_seconds=10``);
  * ``--expect-gauge NAME=VALUE`` (repeatable) requires the summed value
    of NAME's gauge series to EQUAL VALUE — exact, not a floor, because
    the gauges this asserts are topology facts (e.g.
    ``--expect-gauge serving_lanes_ready=8``: a 7-lane fleet is a
    degraded replica, not a lesser success);
  * counter and gauge expectations accept a LABELED selector,
    ``NAME{label=value,...}=N`` — only series carrying every listed label
    pair are summed, and at least one series must match. The lane-drill
    hook (ISSUE 8): ``--expect-gauge 'serving_lane_state{lane=2}=0'``
    asserts lane 2 ended HEALTHY (a specific series, distinguishable from
    "never reported"), ``--expect-counter
    'serving_lane_quarantines_total{lane=2}=1'`` that it was quarantined
    along the way. Histogram expectations stay name-only;
  * ``--expect-gauge-range NAME=LO..HI`` (repeatable) requires EVERY gauge
    series matching the selector to lie in the range INDIVIDUALLY — no
    summing, because fractions don't add — with ``(``/``)`` making a
    bound exclusive. The saturation-drill hook (ISSUE 10):
    ``'serving_lane_busy_fraction=(0..1]'`` asserts every lane did real
    work (one idle lane fails), ``'serving_padding_waste_ratio=[0..1)'``
    that padding stayed sane — property assertions that cannot flake on
    exact values;
  * ``--expect-gauge-sum-range NAME=LO..HI`` (repeatable) requires the
    SUM of every matching gauge series to lie in the range — the
    partition-of-a-whole complement of the per-series form. The ledger
    hook (ISSUE 16): ``'serving_device_time_share=(0..1]'`` asserts the
    stage shares form a pie (each share alone says nothing about the
    total).

trace (``--expect-trace FILE``)
  * FILE is a Chrome/Perfetto ``trace_event`` export (``nm03-trace``
    output): a JSON object whose ``traceEvents`` list is non-empty;
  * duration events come in matching B/E pairs per (pid, tid) with proper
    stack nesting (every E closes the most recent open B of that track,
    names agree, nothing left open at EOF);
  * timestamps are monotonic non-decreasing across the B/E stream
    (metadata ``M`` events are exempt);
  * every serving span (every B event) carries a trace id in its args
    (``trace_ids`` non-empty or ``trace_id``) — the request attribution
    the export exists for.

fleet trace (``--expect-fleet-trace FILE``)
  * FILE is a MERGED multi-log ``nm03-trace`` export (router + replica
    streams, ISSUE 14); everything ``--expect-trace`` checks holds, PLUS:
  * at least two processes carry B events (the router and >=1 replica);
  * at least one ``proxy_hop`` span exists (the router really forwarded);
  * every trace id with a successful (outcome ``ok``) ``proxy_hop``
    resolves to a replica-side span tree — a B event on a DIFFERENT pid
    carrying the same id (a failed-over request resolves through the
    replica that finally answered; requests that completed nowhere are
    exempt — replicas only emit span trees for completed requests).

cross
  * when both artifacts are given, their run_id and git_sha must match.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

SCHEMA_EVENTS = "nm03.events.v1"
SCHEMA_METRICS = "nm03.metrics.v1"
LEVELS = {"DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"}
ENVELOPE = ("schema", "run_id", "git_sha", "seq", "ts_unix", "mono_s", "level", "event")
PATIENT_STATUSES = {"ok", "failed"}
METRIC_TYPES = {"counter", "gauge", "histogram"}
# resilience counters and the labels each series MUST carry
RESILIENCE_LABELS = {
    "resilience_retries_total": ("cause",),
    "resilience_faults_injected_total": ("site", "kind"),
    "pipeline_degraded_total": ("cause",),
}
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SELECTOR_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?$")


def parse_selector(spec: str) -> tuple:
    """``name`` or ``name{label=value,...}`` -> (name, {label: value}).

    The labeled form narrows an expectation to the series carrying every
    listed pair (values compared as strings, optional double quotes
    tolerated: ``lane=2`` and ``lane="2"`` are the same selector).
    Raises ValueError on malformed syntax.
    """
    m = _SELECTOR_RE.match(spec.strip())
    if not m:
        raise ValueError(f"bad metric selector {spec!r}")
    name, raw = m.group(1), m.group(2)
    labels: dict = {}
    if raw is not None:
        if not raw.strip():
            raise ValueError(f"empty label selector in {spec!r}")
        for part in raw.split(","):
            k, eq, v = part.partition("=")
            k, v = k.strip(), v.strip().strip('"')
            if not eq or not _LABEL_RE.match(k) or not v:
                raise ValueError(
                    f"bad label pair {part!r} in selector {spec!r}"
                )
            labels[k] = v
    return name, labels


def _select(series: list, sel: dict) -> list:
    """Values of the (labels, value) series matching every selector pair."""
    return [
        v for lbls, v in series
        if all(lbls.get(k) == want for k, want in sel.items())
    ]


def parse_range(spec: str) -> tuple:
    """``LO..HI`` with optional open-bound brackets -> (lo, hi, lo_open,
    hi_open).

    ``(0..1]`` excludes 0 and includes 1; bare ``0..1`` is inclusive on
    both ends. Open bounds exist because the saturation gates need "in
    (0, 1]": a busy fraction of exactly 0 means the lane never worked,
    and no epsilon floor can express that without flaking.
    """
    raw = spec.strip()
    lo_open = hi_open = False
    # explicit truthiness first: '' is a member of any string, so a bare
    # slice-membership test would IndexError on an empty/bracket-only spec
    # instead of reaching the ValueError the CLI maps to a usage error
    if raw and raw[0] in "([":
        lo_open = raw[0] == "("
        raw = raw[1:]
    if raw and raw[-1] in ")]":
        hi_open = raw[-1] == ")"
        raw = raw[:-1]
    lo_s, sep, hi_s = raw.partition("..")
    if not sep:
        raise ValueError(f"range wants LO..HI, got {spec!r}")
    try:
        return float(lo_s), float(hi_s), lo_open, hi_open
    except ValueError:
        raise ValueError(f"range bounds must be numbers in {spec!r}") from None


def _in_range(v: float, rng: tuple) -> bool:
    lo, hi, lo_open, hi_open = rng
    if v < lo or (lo_open and v == lo):
        return False
    if v > hi or (hi_open and v == hi):
        return False
    return True


def _render_range(rng: tuple) -> str:
    lo, hi, lo_open, hi_open = rng
    return f"{'(' if lo_open else '['}{lo:g}..{hi:g}{')' if hi_open else ']'}"


class Checker:
    def __init__(self):
        self.problems: list[str] = []

    def fail(self, where: str, msg: str) -> None:
        self.problems.append(f"{where}: {msg}")


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def check_events(path: str, chk: Checker, expect_patients: int | None = None):
    """Validate one JSONL event stream; returns (run_id, git_sha) or None."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        chk.fail(path, f"unreadable: {e}")
        return None
    if not lines:
        chk.fail(path, "empty event stream")
        return None

    run_id = git_sha = None
    prev_seq, prev_mono = None, None
    outcomes: dict[str, int] = {}
    events_seen: list[str] = []
    for i, line in enumerate(lines, 1):
        where = f"{path}:{i}"
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            chk.fail(where, f"not valid JSON: {e}")
            continue
        if not isinstance(rec, dict):
            chk.fail(where, "record is not a JSON object")
            continue
        missing = [k for k in ENVELOPE if k not in rec]
        if missing:
            chk.fail(where, f"missing envelope keys: {missing}")
            continue
        if rec["schema"] != SCHEMA_EVENTS:
            chk.fail(where, f"schema {rec['schema']!r} != {SCHEMA_EVENTS!r}")
        if run_id is None:
            run_id, git_sha = rec["run_id"], rec["git_sha"]
            if not run_id:
                chk.fail(where, "empty run_id")
        else:
            if rec["run_id"] != run_id:
                chk.fail(where, f"run_id {rec['run_id']!r} != stream's {run_id!r}")
            if rec["git_sha"] != git_sha:
                chk.fail(where, f"git_sha {rec['git_sha']!r} != stream's {git_sha!r}")
        if not isinstance(rec["seq"], int) or (
            prev_seq is not None and rec["seq"] <= prev_seq
        ):
            chk.fail(where, f"seq {rec['seq']!r} not strictly increasing")
        prev_seq = rec["seq"] if isinstance(rec["seq"], int) else prev_seq
        if not _is_num(rec["ts_unix"]):
            chk.fail(where, f"ts_unix {rec['ts_unix']!r} not numeric")
        if not _is_num(rec["mono_s"]):
            chk.fail(where, f"mono_s {rec['mono_s']!r} not numeric")
        elif prev_mono is not None and rec["mono_s"] < prev_mono:
            chk.fail(where, f"mono_s {rec['mono_s']} went backwards")
        else:
            prev_mono = rec["mono_s"]
        if rec["level"] not in LEVELS:
            chk.fail(where, f"level {rec['level']!r} not in {sorted(LEVELS)}")
        event = rec["event"]
        events_seen.append(event)

        if event == "patient_outcome":
            pid = rec.get("patient_id")
            if not isinstance(pid, str) or not pid:
                chk.fail(where, "patient_outcome without a patient_id")
                pid = f"<line {i}>"
            outcomes[pid] = outcomes.get(pid, 0) + 1
            if rec.get("status") not in PATIENT_STATUSES:
                chk.fail(where, f"patient status {rec.get('status')!r} not in "
                                f"{sorted(PATIENT_STATUSES)}")
            for k in ("slices_total", "slices_ok", "slices_failed",
                      "slices_truncated", "retries"):
                v = rec.get(k)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    chk.fail(where, f"{k} must be a non-negative int, got {v!r}")
            if not isinstance(rec.get("grow_truncated"), bool):
                chk.fail(where, "grow_truncated must be a bool")
            ec = rec.get("error_class")
            if ec is not None and not isinstance(ec, str):
                chk.fail(where, f"error_class must be string or null, got {ec!r}")
            if rec.get("status") == "failed" and rec["level"] != "WARNING":
                chk.fail(where, "failed patient_outcome must be WARNING level")
        elif event == "grow_truncated" and rec["level"] != "WARNING":
            chk.fail(where, "grow_truncated events must be WARNING level")
        elif event == "degraded":
            if rec["level"] != "WARNING":
                chk.fail(where, "degraded events must be WARNING level")
            if not isinstance(rec.get("cause"), str) or not rec.get("cause"):
                chk.fail(where, "degraded event needs a non-empty cause string")
        elif event == "retry":
            if not isinstance(rec.get("cause"), str) or not rec.get("cause"):
                chk.fail(where, "retry event needs a non-empty cause string")
            a = rec.get("attempt")
            if not isinstance(a, int) or isinstance(a, bool) or a < 1:
                chk.fail(where, f"retry attempt must be a positive int, got {a!r}")
        elif event == "fault_injected":
            for k in ("site", "kind"):
                if not isinstance(rec.get(k), str) or not rec.get(k):
                    chk.fail(where, f"fault_injected needs a non-empty {k} string")

    if events_seen and events_seen[0] != "run_started":
        chk.fail(path, f"first event is {events_seen[0]!r}, want 'run_started'")
    if events_seen and events_seen[-1] != "run_finished":
        chk.fail(path, f"last event is {events_seen[-1]!r}, want 'run_finished'")
    for pid, n in sorted(outcomes.items()):
        if n != 1:
            chk.fail(path, f"patient {pid!r} has {n} terminal outcomes, want 1")
    if expect_patients is not None and len(outcomes) != expect_patients:
        chk.fail(path, f"{len(outcomes)} patients with outcomes, "
                       f"expected {expect_patients}")
    return (run_id, git_sha)


def _check_histogram(where: str, rec: dict, chk: Checker) -> None:
    buckets = rec.get("buckets")
    if not isinstance(buckets, list) or not buckets:
        chk.fail(where, "histogram without a buckets list")
        return
    prev = -1
    for j, pair in enumerate(buckets):
        if (not isinstance(pair, list) or len(pair) != 2
                or not isinstance(pair[0], str)
                or not isinstance(pair[1], int) or isinstance(pair[1], bool)):
            chk.fail(where, f"bucket {j} is not [le_string, count]: {pair!r}")
            return
        if pair[1] < prev:
            chk.fail(where, f"bucket counts not cumulative at le={pair[0]}")
        prev = pair[1]
    if buckets[-1][0] != "+Inf":
        chk.fail(where, f"last bucket le is {buckets[-1][0]!r}, want '+Inf'")
    if not (isinstance(rec.get("count"), int) and not isinstance(rec.get("count"), bool)):
        chk.fail(where, f"histogram count must be an int, got {rec.get('count')!r}")
    elif buckets[-1][1] != rec["count"]:
        chk.fail(where, f"+Inf bucket {buckets[-1][1]} != count {rec['count']}")
    if not _is_num(rec.get("sum")):
        chk.fail(where, f"histogram sum must be numeric, got {rec.get('sum')!r}")


def check_metrics(path: str, chk: Checker, expect_counters=None,
                  expect_histograms=None, expect_gauges=None,
                  expect_gauge_ranges=None, expect_gauge_sum_ranges=None):
    """Validate one metrics snapshot; returns (run_id, git_sha) or None.

    ``expect_counters``: {name: min_total | (value, exact)} — the summed
    value across NAME's series must be >= min_total, or (exact form,
    ``NAME==N`` on the CLI) present and EXACTLY equal (chaos-suite and
    compile-cache assertions).
    ``expect_histograms``: {name: min_count} — the summed observation count
    across NAME's histogram series must be >= min_count (and NAME must
    actually be a histogram).
    ``expect_gauges``: {name: value} — the summed value across NAME's gauge
    series must EQUAL value (serving-topology assertions).
    ``expect_gauge_ranges``: {selector: (lo, hi, lo_open, hi_open)} — EVERY
    gauge series matching the selector must lie in the range
    *individually* (no summing: fractions don't add), and at least one
    series must match. ``serving_lane_busy_fraction=(0..1]`` therefore
    asserts every lane worked — one idle lane fails the gate
    (saturation-drill assertions, ISSUE 10).
    ``expect_gauge_sum_ranges``: {selector: range} — the SUM of every gauge
    series matching the selector must lie in the range (at least one
    series must match). The complement of the per-series form for gauges
    that partition a whole: ``serving_device_time_share=(0..1]`` asserts
    the stage shares are a pie — each share alone says nothing about the
    total (ledger assertions, ISSUE 16).
    """
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        chk.fail(path, f"unreadable or not JSON: {e}")
        return None
    if not isinstance(snap, dict):
        chk.fail(path, "snapshot is not a JSON object")
        return None
    if snap.get("schema") != SCHEMA_METRICS:
        chk.fail(path, f"schema {snap.get('schema')!r} != {SCHEMA_METRICS!r}")
    if not _is_num(snap.get("created_unix")):
        chk.fail(path, "created_unix missing or not numeric")
    metrics = snap.get("metrics")
    if not isinstance(metrics, list):
        chk.fail(path, "metrics is not a list")
        return (snap.get("run_id"), snap.get("git_sha"))

    kind_by_name: dict[str, str] = {}
    seen: set[tuple] = set()
    # name -> [(labels, value)] so labeled expectations can select series
    counter_series: dict[str, list] = {}
    gauge_series: dict[str, list] = {}
    histogram_counts: dict[str, int] = {}
    for j, rec in enumerate(metrics):
        where = f"{path}: metrics[{j}]"
        if not isinstance(rec, dict):
            chk.fail(where, "not a JSON object")
            continue
        name, kind, labels = rec.get("name"), rec.get("type"), rec.get("labels")
        if not isinstance(name, str) or not _NAME_RE.match(name or ""):
            chk.fail(where, f"invalid metric name {name!r}")
            continue
        if kind not in METRIC_TYPES:
            chk.fail(where, f"{name}: type {kind!r} not in {sorted(METRIC_TYPES)}")
            continue
        if kind_by_name.setdefault(name, kind) != kind:
            chk.fail(where, f"{name}: conflicting types "
                            f"({kind_by_name[name]} vs {kind})")
        if not isinstance(labels, dict) or not all(
            isinstance(k, str) and _LABEL_RE.match(k) and isinstance(v, str)
            for k, v in labels.items()
        ):
            chk.fail(where, f"{name}: labels must map legal names to strings")
            labels = {}
        key = (name, tuple(sorted(labels.items())))
        if key in seen:
            chk.fail(where, f"duplicate series {name}{labels}")
        seen.add(key)
        if name in RESILIENCE_LABELS:
            if kind != "counter":
                chk.fail(where, f"{name}: must be a counter, is {kind}")
            missing_l = [k for k in RESILIENCE_LABELS[name] if k not in labels]
            if missing_l:
                chk.fail(where, f"{name}: missing required labels {missing_l}")
        if kind == "histogram":
            _check_histogram(where, rec, chk)
            c = rec.get("count")
            if isinstance(c, int) and not isinstance(c, bool):
                histogram_counts[name] = histogram_counts.get(name, 0) + c
        else:
            v = rec.get("value")
            if not _is_num(v):
                chk.fail(where, f"{name}: value must be numeric, got {v!r}")
            elif kind == "counter" and v < 0:
                chk.fail(where, f"{name}: counter value {v} is negative")
            if kind == "counter" and _is_num(v):
                counter_series.setdefault(name, []).append((labels, v))
            if kind == "gauge" and _is_num(v):
                gauge_series.setdefault(name, []).append((labels, v))
    for spec, want in sorted((expect_counters or {}).items()):
        want, exact = want if isinstance(want, tuple) else (want, False)
        try:
            name, sel = parse_selector(spec)
        except ValueError as e:
            chk.fail(path, str(e))
            continue
        series = counter_series.get(name, [])
        if not series and kind_by_name.get(name) not in (None, "counter"):
            chk.fail(path, f"{name} is a {kind_by_name[name]}, not a counter")
            continue
        if exact and name not in counter_series:
            # the exact form asserts presence too: "hits == 0" must fail
            # on a run that never enabled the cache, exactly like a gauge
            chk.fail(path, f"counter {spec} absent, expected == {want}")
            continue
        matched = _select(series, sel)
        if sel and series and not matched:
            chk.fail(path, f"counter {spec}: no series matches the selector")
            continue
        got = sum(matched)
        if exact:
            if got != want:
                chk.fail(path,
                         f"counter {spec} totals {got}, expected == {want}")
        elif got < want:
            chk.fail(path, f"counter {spec} totals {got}, expected >= {want}")
    for spec, want in sorted((expect_gauges or {}).items()):
        try:
            name, sel = parse_selector(spec)
        except ValueError as e:
            chk.fail(path, str(e))
            continue
        if name not in gauge_series:
            kind = kind_by_name.get(name)
            if kind is not None and kind != "gauge":
                chk.fail(path, f"{name} is a {kind}, not a gauge")
            else:
                chk.fail(path, f"gauge {spec} absent, expected == {want}")
            continue
        matched = _select(gauge_series[name], sel)
        if not matched:
            # a labeled selector that matches nothing is ABSENCE, not 0 —
            # "lane 2 healthy (state=0)" must never pass on a fleet that
            # never reported lane 2 at all
            chk.fail(
                path,
                f"gauge {spec}: no series matches, expected == {want}",
            )
            continue
        got = sum(matched)
        if got != want:
            chk.fail(path, f"gauge {spec} totals {got}, expected == {want}")
    for spec, rng in sorted((expect_gauge_ranges or {}).items()):
        try:
            name, sel = parse_selector(spec)
        except ValueError as e:
            chk.fail(path, str(e))
            continue
        if name not in gauge_series:
            kind = kind_by_name.get(name)
            if kind is not None and kind != "gauge":
                chk.fail(path, f"{name} is a {kind}, not a gauge")
            else:
                chk.fail(
                    path,
                    f"gauge {spec} absent, expected in {_render_range(rng)}",
                )
            continue
        matched_series = [
            (lbls, v) for lbls, v in gauge_series[name]
            if all(lbls.get(k) == want for k, want in sel.items())
        ]
        if not matched_series:
            chk.fail(
                path,
                f"gauge {spec}: no series matches, expected in "
                f"{_render_range(rng)}",
            )
            continue
        for lbls, v in matched_series:
            if not _in_range(v, rng):
                chk.fail(
                    path,
                    f"gauge {name}{lbls or ''} = {v}, expected in "
                    f"{_render_range(rng)}",
                )
    for spec, rng in sorted((expect_gauge_sum_ranges or {}).items()):
        try:
            name, sel = parse_selector(spec)
        except ValueError as e:
            chk.fail(path, str(e))
            continue
        if name not in gauge_series:
            kind = kind_by_name.get(name)
            if kind is not None and kind != "gauge":
                chk.fail(path, f"{name} is a {kind}, not a gauge")
            else:
                chk.fail(
                    path,
                    f"gauge {spec} absent, expected sum in "
                    f"{_render_range(rng)}",
                )
            continue
        matched = _select(gauge_series[name], sel)
        if not matched:
            chk.fail(
                path,
                f"gauge {spec}: no series matches, expected sum in "
                f"{_render_range(rng)}",
            )
            continue
        got = sum(matched)
        if not _in_range(got, rng):
            chk.fail(
                path,
                f"gauge {spec} sums to {got:g} over {len(matched)} "
                f"series, expected in {_render_range(rng)}",
            )
    for name, want in sorted((expect_histograms or {}).items()):
        if name not in histogram_counts and kind_by_name.get(name) is not None:
            chk.fail(path, f"{name} is a {kind_by_name[name]}, not a histogram")
            continue
        got = histogram_counts.get(name, 0)
        if got < want:
            chk.fail(
                path,
                f"histogram {name} observation count {got}, expected >= {want}",
            )
    return (snap.get("run_id"), snap.get("git_sha"))


def check_trace(path: str, chk: Checker) -> None:
    """Validate one Chrome/Perfetto trace_event export (nm03-trace output)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        chk.fail(path, f"unreadable or not JSON: {e}")
        return
    events = data.get("traceEvents") if isinstance(data, dict) else None
    if not isinstance(events, list) or not events:
        chk.fail(path, "traceEvents missing or empty")
        return

    stacks: dict[tuple, list] = {}
    prev_ts = None
    b_count = 0
    for i, ev in enumerate(events):
        where = f"{path}: traceEvents[{i}]"
        if not isinstance(ev, dict):
            chk.fail(where, "event is not a JSON object")
            continue
        ph = ev.get("ph")
        if ph == "M":
            continue  # metadata names tracks; no ts contract
        if ph not in ("B", "E"):
            chk.fail(where, f"unexpected phase {ph!r} (want B/E/M)")
            continue
        ts = ev.get("ts")
        if not _is_num(ts):
            chk.fail(where, f"ts {ts!r} not numeric")
            continue
        if prev_ts is not None and ts < prev_ts:
            chk.fail(where, f"ts {ts} went backwards (prev {prev_ts})")
        prev_ts = ts
        key = (ev.get("pid"), ev.get("tid"))
        stack = stacks.setdefault(key, [])
        if ph == "B":
            b_count += 1
            args = ev.get("args")
            has_id = isinstance(args, dict) and (
                (isinstance(args.get("trace_ids"), list) and args["trace_ids"])
                or args.get("trace_id")
            )
            if not has_id:
                chk.fail(
                    where,
                    f"serving span {ev.get('name')!r} carries no trace id "
                    "(args.trace_ids/trace_id)",
                )
            stack.append((ev.get("name"), i))
        else:  # E
            if not stack:
                chk.fail(where, f"E {ev.get('name')!r} with no open B on "
                                f"track {key}")
                continue
            b_name, _ = stack.pop()
            e_name = ev.get("name")
            if e_name is not None and e_name != b_name:
                chk.fail(where, f"E {e_name!r} closes B {b_name!r} "
                                f"(mismatched pair on track {key})")
    for key, stack in sorted(stacks.items(), key=lambda kv: str(kv[0])):
        if stack:
            names = [n for n, _ in stack]
            chk.fail(path, f"track {key} ends with unclosed B events: {names}")
    if b_count == 0:
        chk.fail(path, "no duration (B/E) events — an empty timeline")


def check_fleet_trace(path: str, chk: Checker) -> None:
    """Validate a MERGED fleet timeline (multi-log ``nm03-trace`` output).

    On top of the ordinary trace contract (run :func:`check_trace` too),
    a merged fleet export must show the cross-process attribution the
    merge exists for (ISSUE 14):

    * at least two processes (distinct pids carrying B events) — a
      router log merged with nothing proves nothing;
    * at least one ``proxy_hop`` span (the router really forwarded);
    * **every trace id with a successful (outcome ``ok``) ``proxy_hop``
      resolves to a replica-side span tree**: some B event on a
      DIFFERENT pid carries the same trace id. A failed-over request's
      dead-replica hop resolves through the replica that finally
      answered — the same id, another pid. Requests that never
      completed anywhere (every hop shed/io_error, or a pre-admission
      4xx) are exempt: replicas emit ``serve_trace`` for COMPLETED
      requests only, so demanding resolution there would fail correct
      artifacts from exactly the overload/chaos drills the fleet exists
      for. Probe hops never ride ``proxy_hop`` (canaries span
      ``canary_probe``).
    """
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        chk.fail(path, f"unreadable or not JSON: {e}")
        return
    events = data.get("traceEvents") if isinstance(data, dict) else None
    if not isinstance(events, list) or not events:
        chk.fail(path, "traceEvents missing or empty")
        return

    def ids_of(ev) -> list:
        args = ev.get("args")
        if not isinstance(args, dict):
            return []
        ids = args.get("trace_ids")
        if isinstance(ids, list) and ids:
            return [str(i) for i in ids]
        return [str(args["trace_id"])] if args.get("trace_id") else []

    pids_with_spans: set = set()
    hop_ids: dict[str, tuple] = {}  # trace id -> (pid, event index)
    completed: set = set()  # trace ids with >=1 outcome=ok hop
    any_hops = False
    ids_by_pid: dict[object, set] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or ev.get("ph") != "B":
            continue
        pid = ev.get("pid")
        pids_with_spans.add(pid)
        for tid_ in ids_of(ev):
            ids_by_pid.setdefault(pid, set()).add(tid_)
        if ev.get("name") == "proxy_hop":
            any_hops = True
            args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
            for tid_ in ids_of(ev):
                hop_ids.setdefault(tid_, (pid, i))
                if args.get("outcome") == "ok":
                    completed.add(tid_)
    hop_ids = {t: v for t, v in hop_ids.items() if t in completed}
    if len(pids_with_spans) < 2:
        chk.fail(
            path,
            f"merged fleet trace has {len(pids_with_spans)} process(es) "
            "with spans — want the router AND at least one replica",
        )
    if not any_hops:
        chk.fail(path, "no proxy_hop span — the router never forwarded "
                       "(is this really a fleet log?)")
    for tid_, (pid, i) in sorted(hop_ids.items()):
        resolved = any(
            tid_ in ids and other != pid
            for other, ids in ids_by_pid.items()
        )
        if not resolved:
            chk.fail(
                f"{path}: traceEvents[{i}]",
                f"proxy_hop trace id {tid_!r} resolves to no replica-side "
                "span tree (no B event on another pid carries it)",
            )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--events", default=None, help="JSONL event stream to validate")
    ap.add_argument("--metrics", default=None, help="metrics snapshot JSON to validate")
    ap.add_argument(
        "--expect-patients", type=int, default=None,
        help="require exactly N patients with terminal outcome events",
    )
    ap.add_argument(
        "--expect-counter", action="append", default=[], metavar="NAME=MIN",
        help="require the summed value of counter NAME to be >= MIN; a "
        "NAME{label=value,...} selector narrows to matching series "
        "(repeatable; chaos-suite assertions, e.g. "
        "pipeline_degraded_total=1 or "
        "'serving_lane_quarantines_total{lane=2}=1')",
    )
    ap.add_argument(
        "--expect-histogram", action="append", default=[],
        metavar="NAME=MINCOUNT",
        help="require the summed observation count of histogram NAME to be "
        ">= MINCOUNT (repeatable; serving load/chaos assertions, e.g. "
        "serving_queue_wait_seconds=10)",
    )
    ap.add_argument(
        "--expect-gauge", action="append", default=[], metavar="NAME=VALUE",
        help="require the summed value of gauge NAME to EQUAL VALUE; a "
        "NAME{label=value,...} selector narrows to matching series "
        "(repeatable; serving-topology assertions, e.g. "
        "serving_lanes_ready=8 or 'serving_lane_state{lane=2}=0')",
    )
    ap.add_argument(
        "--expect-gauge-range", action="append", default=[],
        metavar="NAME=LO..HI",
        help="require EVERY gauge series matching NAME (labeled selectors "
        "compose) to lie in the range individually — no summing; '(' / ')' "
        "make a bound exclusive (repeatable; saturation assertions, e.g. "
        "'serving_lane_busy_fraction=(0..1]' = every lane worked, "
        "'serving_padding_waste_ratio=[0..1)')",
    )
    ap.add_argument(
        "--expect-gauge-sum-range", action="append", default=[],
        metavar="NAME=LO..HI",
        help="require the SUM of every gauge series matching NAME to lie "
        "in the range — the partition-of-a-whole complement of "
        "--expect-gauge-range (repeatable; ledger assertions, e.g. "
        "'serving_device_time_share=(0..1]' = the stage shares are a "
        "pie, ISSUE 16)",
    )
    ap.add_argument(
        "--expect-trace", action="append", default=[], metavar="FILE",
        help="validate a Perfetto/Chrome trace_event export (nm03-trace "
        "output): non-empty, monotonic ts, matched B/E pairs, every "
        "serving span carrying a trace id (repeatable)",
    )
    ap.add_argument(
        "--expect-fleet-trace", action="append", default=[], metavar="FILE",
        help="validate a MERGED fleet timeline (multi-log nm03-trace "
        "output): everything --expect-trace checks PLUS >=2 processes, "
        ">=1 proxy_hop span, and every SUCCESSFUL proxy_hop trace id "
        "resolving to a replica-side span tree on another pid "
        "(repeatable)",
    )
    args = ap.parse_args(argv)
    if (
        not args.events and not args.metrics and not args.expect_trace
        and not args.expect_fleet_trace
    ):
        ap.error(
            "nothing to check: pass --events, --metrics, --expect-trace "
            "and/or --expect-fleet-trace"
        )

    def parse_expectations(
        specs: list, flag: str, labeled: bool = False,
        allow_exact: bool = False,
    ) -> dict:
        out = {}
        for spec in specs:
            # rpartition: a labeled selector (NAME{label=value}=N) carries
            # '=' inside the braces; the expectation value is always last
            sel, _, val = spec.rpartition("=")
            exact = False
            if allow_exact and sel.endswith("="):
                # NAME==N / NAME{...}==N: exact, gauge-style equality
                sel = sel[:-1]
                exact = True
            try:
                out[sel] = (float(val), exact) if allow_exact else float(val)
            except ValueError:
                ap.error(f"{flag} wants NAME=N or NAME{{label=value}}=N, "
                         f"got {spec!r}")
            if labeled:
                try:
                    parse_selector(sel)
                except ValueError as e:
                    ap.error(f"{flag}: {e}")
            elif not _NAME_RE.match(sel):
                ap.error(f"{flag} wants a plain metric NAME, got {sel!r}")
        if out and not args.metrics:
            ap.error(f"{flag} needs --metrics")
        return out

    expect_counters = parse_expectations(
        args.expect_counter, "--expect-counter", labeled=True,
        allow_exact=True,
    )
    expect_histograms = parse_expectations(
        args.expect_histogram, "--expect-histogram"
    )
    expect_gauges = parse_expectations(
        args.expect_gauge, "--expect-gauge", labeled=True
    )
    def parse_range_expectations(specs: list, flag: str) -> dict:
        out = {}
        for spec in specs:
            sel, _, val = spec.rpartition("=")
            try:
                parse_selector(sel)
                out[sel] = parse_range(val)
            except ValueError as e:
                ap.error(f"{flag}: {e}")
        if out and not args.metrics:
            ap.error(f"{flag} needs --metrics")
        return out

    expect_gauge_ranges = parse_range_expectations(
        args.expect_gauge_range, "--expect-gauge-range"
    )
    expect_gauge_sum_ranges = parse_range_expectations(
        args.expect_gauge_sum_range, "--expect-gauge-sum-range"
    )

    chk = Checker()
    ev_ident = mt_ident = None
    if args.events:
        ev_ident = check_events(args.events, chk, args.expect_patients)
    if args.metrics:
        mt_ident = check_metrics(
            args.metrics, chk, expect_counters, expect_histograms,
            expect_gauges, expect_gauge_ranges, expect_gauge_sum_ranges,
        )
    for trace_path in args.expect_trace:
        check_trace(trace_path, chk)
    for trace_path in args.expect_fleet_trace:
        check_trace(trace_path, chk)  # the base contract holds merged too
        check_fleet_trace(trace_path, chk)
    if ev_ident and mt_ident:
        if mt_ident[0] != ev_ident[0]:
            chk.fail("cross", f"metrics run_id {mt_ident[0]!r} != "
                              f"events run_id {ev_ident[0]!r}")
        if mt_ident[1] != ev_ident[1]:
            chk.fail("cross", f"metrics git_sha {mt_ident[1]!r} != "
                              f"events git_sha {ev_ident[1]!r}")

    for p in chk.problems:
        print(f"DRIFT {p}", file=sys.stderr)
    if chk.problems:
        print(f"check_telemetry: {len(chk.problems)} violation(s)", file=sys.stderr)
        return 1
    checked = " and ".join(
        p for p in (
            args.events, args.metrics, *args.expect_trace,
            *args.expect_fleet_trace,
        ) if p
    )
    print(f"check_telemetry: OK ({checked})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
